"""Sparse-row update path (SelectedRows analog) — VERDICT r2 item 6.

Pins the three claims of :mod:`paddle_tpu.optim.sparse`:
1. the sparse path reproduces the dense path exactly for the lazy-correct
   optimizers (sgd / adagrad / ftrl) on a small table;
2. lazy L2 catch-up reproduces dense SGD+L2;
3. nothing [vocab, D]-shaped enters the autodiff graph — every table-shaped
   value produced inside the step is a commit scatter (the structural
   guarantee that tables ≫ the dense-grad memory budget stay trainable,
   reference: ``SparseRowMatrix.h:31``, ``RemoteParameterUpdater.h:265``).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import optim
from paddle_tpu.models.ctr import (SparseRowsWideDeepCTR, WideDeepCTR,
                                   make_sparse_ctr_step)
from paddle_tpu.nn import costs
from paddle_tpu.optim import sparse as sp
from paddle_tpu.optim.optimizers import apply_updates

FIELDS, VOCAB = 4, 30


@pytest.fixture
def nprng():
    return np.random.RandomState(0)


def _batches(nprng, n_steps, batch=16, weighted=False):
    out = []
    for _ in range(n_steps):
        ids = nprng.randint(0, VOCAB, size=(batch, FIELDS)).astype(np.int32)
        ids[nprng.rand(*ids.shape) < 0.1] = -1          # padding
        y = (nprng.rand(batch) < 0.4).astype(np.int32)
        b = {"ids": jnp.asarray(ids), "label": jnp.asarray(y)}
        if weighted:                                     # sparse float slot
            b["weights"] = jnp.asarray(
                nprng.normal(size=ids.shape).astype(np.float32))
        out.append(b)
    return out


def _loss(out, batch):
    return jnp.mean(costs.binary_logistic(out, batch["label"]))


def _init_pair(nprng, emb_dim=8):
    """Dense model + sparse twin with identical initial values."""
    dense = WideDeepCTR(FIELDS, VOCAB, emb_dim=emb_dim, hidden=(16,),
                        name="ctr")
    sparse = SparseRowsWideDeepCTR(FIELDS, VOCAB, emb_dim=emb_dim,
                                   hidden=(16,), name="ctr")
    ids0 = jnp.zeros((2, FIELDS), jnp.int32)
    dvars = dense.init(jax.random.PRNGKey(0), ids0)
    dparams = dvars["params"]
    wide_w = dparams["ctr"]["wide"]["w"]
    deep_w = dparams["ctr"]["deep"]["w"]
    sparams = {"ctr": {k: v for k, v in dparams["ctr"].items()
                       if k not in ("wide", "deep")}}
    return dense, sparse, dparams, sparams, wide_w, deep_w


def _run_dense(dense, dparams, optimizer, batches):
    opt_state = optimizer.init(dparams)
    params = dparams
    for i, b in enumerate(batches):
        def loss_fn(p):
            return _loss(dense.apply({"params": p}, b["ids"],
                                     weights=b.get("weights")), b)
        _, g = jax.value_and_grad(loss_fn)(params)
        upd, opt_state = optimizer.update(g, opt_state, params,
                                          jnp.asarray(i))
        params = apply_updates(params, upd)
    return params


def _run_sparse(sparse, sparams, wide_w, deep_w, dense_opt, row_opt,
                batches, catchup=None):
    step = make_sparse_ctr_step(sparse, dense_opt, row_opt, _loss,
                                catchup=catchup)
    wide_tbl = sp.SparseTable(wide_w, row_opt.init(wide_w),
                              jnp.full((wide_w.shape[0],), -1, jnp.int32))
    deep_tbl = sp.SparseTable(deep_w, row_opt.init(deep_w),
                              jnp.full((deep_w.shape[0],), -1, jnp.int32))
    params, opt_state = sparams, dense_opt.init(sparams)
    for i, b in enumerate(batches):
        params, opt_state, wide_tbl, deep_tbl, loss = step(
            params, opt_state, wide_tbl, deep_tbl, jnp.asarray(i), b)
    return params, wide_tbl, deep_tbl, float(loss)


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad", "ftrl"])
def test_sparse_path_matches_dense(nprng, opt_name):
    """Sparse rows == dense table training for the lazy-correct rules (the
    local-vs-remote oracle of test_CompareSparse.cpp applied to this tier)."""
    make = {"sgd": lambda: optim.sgd(0.1),
            "adagrad": lambda: optim.adagrad(0.1),
            "ftrl": lambda: optim.ftrl(0.1, lambda1=0.01, lambda2=0.01)}
    batches = _batches(nprng, 6)
    dense, sparse, dparams, sparams, wide_w, deep_w = _init_pair(nprng)
    if opt_name == "ftrl":
        # FTRL's param is a pure function of (z, n): a dense run resets
        # untouched rows to that fixed point on the very first step, while
        # the lazy path leaves them untouched until hit (the reference's
        # sparse semantics). Equivalence holds from the fixed point — the
        # standard zero init for sparse LR tables.
        wide_w = jnp.zeros_like(wide_w)
        deep_w = jnp.zeros_like(deep_w)
        dparams = jax.tree_util.tree_map(lambda x: x, dparams)
        dparams["ctr"]["wide"]["w"] = wide_w
        dparams["ctr"]["deep"]["w"] = deep_w
    dfinal = _run_dense(dense, dparams, make[opt_name](), batches)
    sfinal, wide_tbl, deep_tbl, _ = _run_sparse(
        sparse, sparams, wide_w, deep_w, make[opt_name](), make[opt_name](),
        batches)
    np.testing.assert_allclose(np.asarray(wide_tbl.rows),
                               np.asarray(dfinal["ctr"]["wide"]["w"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(deep_tbl.rows),
                               np.asarray(dfinal["ctr"]["deep"]["w"]),
                               rtol=1e-5, atol=1e-6)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(sfinal)[0],
            jax.tree_util.tree_flatten_with_path(
                {"ctr": {"mlp": dfinal["ctr"]["mlp"]}})[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=str(pa))


def test_lazy_l2_catchup_matches_dense_decay(nprng):
    """Sparse SGD+L2 with closed-form idle catch-up == dense SGD + L2 which
    decays every row every step (Regularizer.cpp lazy path)."""
    lr, decay = 0.1, 0.05
    batches = _batches(nprng, 6, batch=4)    # small batches -> idle rows
    dense, sparse, dparams, sparams, wide_w, deep_w = _init_pair(nprng)
    dfinal = _run_dense(dense, dparams,
                        optim.chain(optim.weight_decay(decay),
                                    optim.sgd(lr)), batches)
    sfinal, wide_tbl, deep_tbl, _ = _run_sparse(
        sparse, sparams, wide_w, deep_w,
        optim.chain(optim.weight_decay(decay), optim.sgd(lr)),
        optim.chain(optim.weight_decay(decay), optim.sgd(lr)),
        batches, catchup=sp.l2_catchup(lr, decay))

    # Lazy semantics: rows idle since their last touch are STALE in storage
    # (their decay is applied at next prefetch). Equivalence is therefore a
    # read-time property — flush the pending catch-up before comparing.
    n = len(batches)

    def flush(tbl):
        idle = np.where(np.asarray(tbl.last_step) < 0, n,
                        n - 1 - np.asarray(tbl.last_step))
        f = (1.0 - lr * decay) ** idle.astype(np.float64)
        return np.asarray(tbl.rows) * f[:, None]

    np.testing.assert_allclose(flush(deep_tbl),
                               np.asarray(dfinal["ctr"]["deep"]["w"]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(flush(wide_tbl),
                               np.asarray(dfinal["ctr"]["wide"]["w"]),
                               rtol=1e-4, atol=1e-6)


def test_no_table_shaped_values_outside_commit(nprng):
    """Structural memory-budget guarantee: with a table far larger than the
    batch working set, the ONLY table-shaped values produced inside the step
    are the commit scatters (in-place under donation). A dense-gradient
    implementation would materialise [vocab, D] adds/selects from autodiff —
    exactly what made tables ≫ device memory untrainable."""
    big_vocab = 100_000
    emb_dim = 32
    sparse = SparseRowsWideDeepCTR(4, big_vocab // 4, emb_dim=emb_dim,
                                   hidden=(16,), name="ctr")
    ids = jnp.zeros((8, 4), jnp.int32)
    batch = {"ids": ids, "label": jnp.zeros((8,), jnp.int32)}
    sparams = sparse.init(jax.random.PRNGKey(0), ids,
                          jnp.zeros((32, 1)), jnp.zeros((8, 4), jnp.int32),
                          jnp.zeros((32, emb_dim)),
                          jnp.zeros((8, 4), jnp.int32))["params"]
    row_opt = optim.adagrad(0.1)
    dense_opt = optim.sgd(0.1)
    wide_w = jnp.zeros((big_vocab, 1))
    deep_w = jnp.zeros((big_vocab, emb_dim))
    wide_tbl = sp.SparseTable(wide_w, row_opt.init(wide_w),
                              jnp.full((big_vocab,), -1, jnp.int32))
    deep_tbl = sp.SparseTable(deep_w, row_opt.init(deep_w),
                              jnp.full((big_vocab,), -1, jnp.int32))
    step = make_sparse_ctr_step(sparse, dense_opt, row_opt, _loss)
    jaxpr = jax.make_jaxpr(step._raw)(
        sparams, dense_opt.init(sparams), wide_tbl, deep_tbl,
        jnp.asarray(0), batch)

    offenders = []

    def walk(jpr):
        for eqn in jpr.eqns:
            for sub in (p for p in eqn.params.values()
                        if hasattr(p, "jaxpr")):
                walk(sub.jaxpr)
            for ov in eqn.outvars:
                shape = getattr(ov.aval, "shape", ())
                if shape and shape[0] == big_vocab \
                        and eqn.primitive.name != "scatter":
                    offenders.append((eqn.primitive.name, shape))

    walk(jaxpr.jaxpr)
    assert not offenders, offenders

    # and the gradient wrt rows really is [U, D]-shaped, U = ids.size
    out = step(sparams, dense_opt.init(sparams), wide_tbl, deep_tbl,
               jnp.asarray(0), batch)
    assert out[2].rows.shape == (big_vocab, 1)
    assert np.isfinite(float(out[4]))


def test_sparse_ctr_e2e_loss_decreases(nprng):
    """End-to-end: the sparse path actually learns (loss decreases) with
    FTRL rows + Adam dense — the quick_start sparse acceptance run."""
    dense, sparse, dparams, sparams, wide_w, deep_w = _init_pair(nprng)
    rng = np.random.RandomState(1)
    # learnable synthetic rule: label depends on one field's id parity
    batches = []
    for _ in range(100):
        ids = rng.randint(0, VOCAB, size=(32, FIELDS)).astype(np.int32)
        y = (ids[:, 0] % 2).astype(np.int32)
        batches.append({"ids": jnp.asarray(ids), "label": jnp.asarray(y)})
    row_opt = optim.ftrl(0.5, lambda1=0.001, lambda2=0.001)
    step = make_sparse_ctr_step(sparse, optim.adam(1e-2), row_opt, _loss)
    wide_tbl = sp.SparseTable(wide_w, row_opt.init(wide_w),
                              jnp.full((wide_w.shape[0],), -1, jnp.int32))
    deep_tbl = sp.SparseTable(deep_w, row_opt.init(deep_w),
                              jnp.full((deep_w.shape[0],), -1, jnp.int32))
    params, opt_state = sparams, optim.adam(1e-2).init(sparams)
    losses = []
    for i, b in enumerate(batches):
        params, opt_state, wide_tbl, deep_tbl, loss = step(
            params, opt_state, wide_tbl, deep_tbl, jnp.asarray(i), b)
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < 0.55 * np.mean(losses[:5]), losses


def test_host_offloaded_table_matches_device_path(nprng):
    """HostSparseTable (storage in host RAM, only [U, D] rows on device)
    must reproduce the device-resident sparse path exactly — the
    tables->bigger-than-HBM regime the reference served with pservers."""
    V, D = 64, 8
    rows0 = nprng.normal(size=(V, D)).astype(np.float32)
    opt_dev = optim.adagrad(0.1)
    opt_host = optim.adagrad(0.1)
    dev_tbl = sp.SparseTable(jnp.asarray(rows0), opt_dev.init(
        jnp.asarray(rows0)), jnp.full((V,), -1, jnp.int32))
    host_tbl = sp.HostSparseTable(rows0.copy(), opt_host)

    rng = np.random.RandomState(3)
    for step in range(5):
        ids = rng.randint(-1, V, size=(6, 3)).astype(np.int32)
        target = jnp.asarray(rng.normal(size=(6, 3, D)).astype(np.float32))

        # device path
        pre = sp.sparse_prefetch(dev_tbl, jnp.asarray(ids),
                                 jnp.asarray(step))

        def loss_dev(r):
            e = jnp.where((jnp.asarray(ids) >= 0)[..., None],
                          r[pre.gather_idx], 0.0)
            return jnp.mean((e - target) ** 2)

        g = jax.grad(loss_dev)(pre.rows)
        upd, slots = opt_dev.update(g, pre.slots, pre.rows,
                                    jnp.asarray(step))
        dev_tbl = sp.sparse_commit(dev_tbl, pre, pre.rows + upd, slots,
                                   step)

        # host path
        uniq, gidx, rows, hslots = host_tbl.prefetch(ids, step)

        def loss_host(r):
            e = jnp.where((jnp.asarray(ids) >= 0)[..., None], r[gidx], 0.0)
            return jnp.mean((e - target) ** 2)

        gh = jax.grad(loss_host)(rows)
        uh, new_hslots = opt_host.update(gh, hslots, rows, jnp.asarray(step))
        host_tbl.commit(uniq, np.asarray(rows + uh), new_hslots, step)

    np.testing.assert_allclose(host_tbl.rows, np.asarray(dev_tbl.rows),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(
        jax.tree_util.tree_leaves(host_tbl.slots)[0],
        np.asarray(jax.tree_util.tree_leaves(dev_tbl.slots)[0]),
        rtol=1e-6, atol=1e-7)


def test_host_table_static_shapes_compile_once(nprng):
    """HostSparseTable.prefetch pads unique rows to a FIXED U = ids.size
    (sentinel id = vocab, zero rows), so a jitted consumer of the [U, D]
    working set compiles ONCE across batches with different duplicate
    structure — the reference's fixed working set (CacheRowCpuMatrix,
    ``math/SparseRowMatrix.h``)."""
    V, D = 32, 4
    tbl = sp.HostSparseTable(
        nprng.normal(size=(V, D)).astype(np.float32), optim.sgd(0.1))

    consumer = jax.jit(lambda rows, gidx: jnp.sum(rows[gidx] ** 2))
    rng = np.random.RandomState(0)
    batches = [
        np.zeros((4, 2), np.int32),                      # 1 unique id
        rng.randint(0, V, size=(4, 2)).astype(np.int32),  # many unique
        np.full((4, 2), -1, np.int32),                   # all padding
    ]
    seen_U = set()
    for step, ids in enumerate(batches):
        uniq, gidx, rows, _ = tbl.prefetch(ids, step)
        assert uniq.shape[0] == ids.size
        seen_U.add(rows.shape)
        consumer(rows, jnp.asarray(gidx))
    assert seen_U == {(batches[0].size, D)}
    assert consumer._cache_size() == 1

    # commit still drops the sentinel padding slots
    uniq, gidx, rows, slots = tbl.prefetch(batches[0], 10)
    before = tbl.rows.copy()
    tbl.update(uniq, jnp.ones_like(rows), rows, slots, 10)
    changed = np.where(np.any(tbl.rows != before, axis=1))[0]
    np.testing.assert_array_equal(changed, [0])


def test_host_offloaded_lazy_catchup(nprng):
    """Host table applies the same closed-form idle-decay catch-up."""
    V, D, lr, decay = 16, 4, 0.1, 0.05
    rows0 = np.ones((V, D), np.float32)
    tbl = sp.HostSparseTable(rows0.copy(),
                             optim.chain(optim.weight_decay(decay),
                                         optim.sgd(lr)),
                             catchup=sp.l2_catchup(lr, decay))
    # touch row 0 at step 0, then row 0 again at step 3: catch-up must
    # apply (1-lr*decay)^2 for the idle steps 1, 2
    ids = np.array([[0]], np.int32)
    uniq, gidx, rows, slots = tbl.prefetch(ids, 0)
    tbl.update(uniq, jnp.zeros_like(rows), rows, slots, 0)
    v_after0 = tbl.rows[0].copy()
    uniq, gidx, rows, slots = tbl.prefetch(ids, 3)
    want = v_after0 * (1 - lr * decay) ** 2
    np.testing.assert_allclose(np.asarray(rows)[0], want, rtol=1e-6)


def test_sparse_float_slot_sparse_path_matches_dense(nprng):
    """The weighted (sparse float-value) slot trains identically through
    the sparse-rows tier and the dense path — weights scale the row
    gradients, so this also pins the weighted scatter-add."""
    batches = _batches(nprng, 5, weighted=True)
    dense, sparse, dparams, sparams, wide_w, deep_w = _init_pair(nprng)
    dfinal = _run_dense(dense, dparams, optim.sgd(0.1), batches)
    sfinal, wide_tbl, deep_tbl, _ = _run_sparse(
        sparse, sparams, wide_w, deep_w, optim.sgd(0.1), optim.sgd(0.1),
        batches)
    np.testing.assert_allclose(np.asarray(wide_tbl.rows),
                               np.asarray(dfinal["ctr"]["wide"]["w"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(deep_tbl.rows),
                               np.asarray(dfinal["ctr"]["deep"]["w"]),
                               rtol=1e-5, atol=1e-6)
