"""Numeric-oracle tests for the long-tail layer set: LRN, RowConv, 3-D
conv/pool, MDLstm, SelectiveFC, SamplingId, cross_entropy_over_beam
(the analog of the reference's per-layer cases in ``test_LayerGrad.cpp``)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nn.layers import (Conv3D, Conv3DTranspose, CrossMapNormal,
                                  Pool3D, RowConv, SamplingId, SelectiveFC,
                                  Linear)
from paddle_tpu.nn.recurrent import MDLstm


# --------------------------------------------------------------------- LRN

def test_cross_map_normal_vs_oracle():
    rng = np.random.RandomState(0)
    x = rng.normal(size=(2, 4, 4, 6)).astype(np.float32)
    size, scale, power = 5, 0.01, 0.75
    mod = CrossMapNormal(size=size, scale=scale, power=power)
    got = np.asarray(mod.apply({}, jnp.asarray(x)))
    half = (size - 1) // 2
    want = np.empty_like(x)
    C = x.shape[-1]
    for c in range(C):
        lo, hi = max(0, c - half), min(C, c - half + size)
        s = (x[..., lo:hi] ** 2).sum(-1)
        want[..., c] = x[..., c] * (1.0 + scale * s) ** (-power)
    np.testing.assert_allclose(got, want, rtol=1e-5)


# ----------------------------------------------------------------- rowconv

def test_row_conv_vs_oracle_and_grad():
    rng = np.random.RandomState(1)
    B, T, D, K = 2, 7, 3, 3
    x = rng.normal(size=(B, T, D)).astype(np.float32)
    lengths = np.array([7, 4])
    w = rng.normal(size=(K, D)).astype(np.float32)
    mod = RowConv(context=K)
    params = mod.init(jax.random.PRNGKey(0), jnp.asarray(x),
                      jnp.asarray(lengths))
    # overwrite the (zero-init) filter with random weights
    tree = params["params"]
    node = tree[next(iter(tree))]
    node["w"] = jnp.asarray(w)
    got = np.asarray(mod.apply(params, jnp.asarray(x), jnp.asarray(lengths)))
    want = np.zeros_like(x)
    for b in range(B):
        for t in range(lengths[b]):
            for k in range(K):
                if t + k < lengths[b]:
                    want[b, t] += x[b, t + k] * w[k]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def loss(p):
        return jnp.sum(mod.apply(p, jnp.asarray(x), jnp.asarray(lengths)) ** 2)
    g = jax.grad(loss)(params)
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(l)).all() for l in flat)


# ---------------------------------------------------------------------- 3D

def test_conv3d_vs_oracle():
    rng = np.random.RandomState(2)
    x = rng.normal(size=(1, 3, 4, 4, 2)).astype(np.float32)
    mod = Conv3D(features=3, kernel=2, stride=1, padding="VALID",
                 use_bias=False)
    params = mod.init(jax.random.PRNGKey(0), jnp.asarray(x))
    w = np.asarray(jax.tree_util.tree_leaves(params["params"])[0])
    got = np.asarray(mod.apply(params, jnp.asarray(x)))
    assert got.shape == (1, 2, 3, 3, 3)
    want = np.zeros_like(got)
    for d in range(2):
        for i in range(3):
            for j in range(3):
                patch = x[0, d:d + 2, i:i + 2, j:j + 2, :]
                want[0, d, i, j] = np.tensordot(patch, w, axes=([0, 1, 2, 3],
                                                                [0, 1, 2, 3]))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_pool3d_max_and_avg():
    x = jnp.arange(2 * 2 * 2 * 4 * 1, dtype=jnp.float32).reshape(2, 2, 2, 4, 1)
    mx = Pool3D("max", window=2, stride=2).apply({}, x)
    av = Pool3D("avg", window=2, stride=2).apply({}, x)
    assert mx.shape == (2, 1, 1, 2, 1)
    xs = np.asarray(x)
    np.testing.assert_allclose(np.asarray(mx)[0, 0, 0, 0, 0],
                               xs[0, :2, :2, :2].max())
    np.testing.assert_allclose(np.asarray(av)[0, 0, 0, 0, 0],
                               xs[0, :2, :2, :2].mean())


def test_conv3d_transpose_shape_inverts_stride():
    x = jnp.ones((1, 2, 3, 3, 2))
    mod = Conv3DTranspose(features=4, kernel=2, stride=2, padding="SAME")
    params = mod.init(jax.random.PRNGKey(0), x)
    y = mod.apply(params, x)
    assert y.shape == (1, 4, 6, 6, 4)


# ------------------------------------------------------------------ MDLstm

def test_mdlstm_vs_python_recurrence():
    rng = np.random.RandomState(3)
    B, H, W, D, hd = 2, 3, 4, 3, 5
    x = rng.normal(size=(B, H, W, D)).astype(np.float32)
    mod = MDLstm(hidden=hd)
    params = mod.init(jax.random.PRNGKey(0), jnp.asarray(x))
    got = np.asarray(mod.apply(params, jnp.asarray(x)))
    assert got.shape == (B, H, W, hd)

    tree = params["params"][next(iter(params["params"]))]
    wx, wh_up, wh_left, b = (np.asarray(tree["wx"]), np.asarray(tree["wh_up"]),
                             np.asarray(tree["wh_left"]), np.asarray(tree["b"]))
    sig = lambda v: 1 / (1 + np.exp(-v))
    hbuf = np.zeros((B, H, W, hd))
    cbuf = np.zeros((B, H, W, hd))
    for i in range(H):
        for j in range(W):
            h_up = hbuf[:, i - 1, j] if i else np.zeros((B, hd))
            c_up = cbuf[:, i - 1, j] if i else np.zeros((B, hd))
            h_l = hbuf[:, i, j - 1] if j else np.zeros((B, hd))
            c_l = cbuf[:, i, j - 1] if j else np.zeros((B, hd))
            z = x[:, i, j] @ wx + b + h_up @ wh_up + h_l @ wh_left
            zi, zf1, zf2, zg, zo = np.split(z, 5, axis=-1)
            c = sig(zf1) * c_up + sig(zf2) * c_l + sig(zi) * np.tanh(zg)
            hbuf[:, i, j] = sig(zo) * np.tanh(c)
            cbuf[:, i, j] = c
    np.testing.assert_allclose(got, hbuf, rtol=1e-4, atol=1e-5)


def test_mdlstm_reverse_directions_differ():
    x = jnp.asarray(np.random.RandomState(0).normal(
        size=(1, 3, 3, 2)).astype(np.float32))
    m1 = MDLstm(hidden=4)
    p = m1.init(jax.random.PRNGKey(0), x)
    m2 = MDLstm(hidden=4, reverse_h=True, reverse_w=True)
    y1 = m1.apply(p, x)
    y2 = m2.apply(p, x)
    assert not np.allclose(np.asarray(y1), np.asarray(y2))


# ------------------------------------------------------------- SelectiveFC

def test_selective_fc_matches_full_columns():
    rng = np.random.RandomState(4)
    B, D, F, K = 3, 5, 11, 4
    x = rng.normal(size=(B, D)).astype(np.float32)
    sel = np.stack([rng.choice(F, K, replace=False) for _ in range(B)])
    sel[0, -1] = -1                          # padding id
    mod = SelectiveFC(features=F)
    params = mod.init(jax.random.PRNGKey(0), jnp.asarray(x))
    full = np.asarray(mod.apply(params, jnp.asarray(x)))
    part = np.asarray(mod.apply(params, jnp.asarray(x), jnp.asarray(sel)))
    for b in range(B):
        for k in range(K):
            if sel[b, k] < 0:
                assert part[b, k] == 0.0
            else:
                np.testing.assert_allclose(part[b, k], full[b, sel[b, k]],
                                           rtol=1e-5, atol=1e-6)


# -------------------------------------------------------------- SamplingId

def test_sampling_id_follows_distribution():
    logits = jnp.log(jnp.asarray([[0.8, 0.1, 0.1]] * 4000, jnp.float32))
    mod = SamplingId()
    ids = mod.apply({}, logits, rngs={"sample": jax.random.PRNGKey(0)})
    frac0 = float(np.mean(np.asarray(ids) == 0))
    assert 0.75 < frac0 < 0.85


# -------------------------------------------------- cross_entropy_over_beam

def test_cross_entropy_over_beam_semantics():
    from paddle_tpu.nn.costs import cross_entropy_over_beam
    scores = jnp.asarray([[1.0, 2.0, 3.0]])
    # gold in beam: plain softmax CE over the 3 candidates
    got = float(cross_entropy_over_beam(scores, jnp.asarray([1])))
    want = float(-jax.nn.log_softmax(scores[0])[1])
    assert abs(got - want) < 1e-6
    # gold off beam: appended as an extra path with its own score
    got2 = float(cross_entropy_over_beam(scores, jnp.asarray([-1]),
                                         gold_score=jnp.asarray([4.0])))
    ext = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    want2 = float(-jax.nn.log_softmax(ext)[3])
    assert abs(got2 - want2) < 1e-6
    # padding candidates are masked out
    got3 = float(cross_entropy_over_beam(
        jnp.asarray([[1.0, 2.0, -5.0]]), jnp.asarray([1]),
        valid_mask=jnp.asarray([[True, True, False]])))
    want3 = float(-jax.nn.log_softmax(jnp.asarray([1.0, 2.0]))[1])
    assert abs(got3 - want3) < 1e-6


def test_conv3d_grad_under_bf16_policy():
    from paddle_tpu.core import dtypes
    x = jnp.ones((1, 3, 4, 4, 2))
    mod = Conv3D(features=2, kernel=2, padding=1)
    params = mod.init(jax.random.PRNGKey(0), x)
    with dtypes.use_policy(dtypes.bfloat16_compute):
        g = jax.grad(lambda p: jnp.sum(mod.apply(p, x)))(params)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(g))
    y = Conv3DTranspose(features=2, kernel=2, padding=1).apply(
        Conv3DTranspose(features=2, kernel=2, padding=1).init(
            jax.random.PRNGKey(0), x), x)
    assert y.ndim == 5


def test_scale_sub_region_vs_oracle():
    from paddle_tpu.nn.layers import ScaleSubRegion
    rng = np.random.RandomState(5)
    x = rng.normal(size=(2, 4, 5, 3)).astype(np.float32)
    # per-sample 1-based inclusive [c1,c2,h1,h2,w1,w2]
    idx = np.array([[1, 2, 2, 3, 1, 5],
                    [3, 3, 1, 4, 2, 2]], np.int32)
    mod = ScaleSubRegion(value=2.0)
    got = np.asarray(mod.apply({}, jnp.asarray(x), jnp.asarray(idx)))
    want = x.copy()
    for b in range(2):
        c1, c2, h1, h2, w1, w2 = idx[b]
        want[b, h1-1:h2, w1-1:w2, c1-1:c2] *= 2.0
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # gradient flows scaled only in-region (reference backward :73)
    g = jax.grad(lambda x: jnp.sum(mod.apply({}, x, jnp.asarray(idx))))(
        jnp.asarray(x))
    gw = np.ones_like(x)
    for b in range(2):
        c1, c2, h1, h2, w1, w2 = idx[b]
        gw[b, h1-1:h2, w1-1:w2, c1-1:c2] = 2.0
    np.testing.assert_allclose(np.asarray(g), gw, rtol=1e-6)


def test_merge_model_and_dump_config(tmp_path):
    import json
    from paddle_tpu.inference import dump_config, merge_model, infer
    from paddle_tpu.nn.layers import Linear
    m = Linear(3)
    v = m.init(jax.random.PRNGKey(0), jnp.ones((2, 4)))
    d = merge_model(str(tmp_path / "deploy"), m, v)
    out = infer(d, jnp.ones((2, 4)))
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(m.apply(v, jnp.ones((2, 4)))),
                               rtol=1e-6)
    cfg = json.loads(dump_config(m))
    assert cfg["modules"] and "root" in cfg


# ----------------------------------------------------- final small layers

def test_small_elementwise_layers_vs_oracle():
    from paddle_tpu.nn.layers import (BilinearInterp, ConvexCombination,
                                      CosSimVecMat, DotProd, EosIdCheck,
                                      Power, PRelu, Scaling,
                                      ScalingProjection, SliceProjection,
                                      SwitchOrder,
                                      TransposedFullMatrixProjection)
    rng = np.random.RandomState(0)
    x = rng.normal(size=(3, 5)).astype(np.float32)
    w = rng.uniform(0.5, 2.0, size=(3,)).astype(np.float32)

    got = Power().apply({}, jnp.asarray(w), jnp.asarray(np.abs(x)))
    np.testing.assert_allclose(np.asarray(got), np.abs(x) ** w[:, None],
                               rtol=1e-5)
    got = Scaling().apply({}, jnp.asarray(w), jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), w[:, None] * x, rtol=1e-6)
    y = rng.normal(size=(3, 5)).astype(np.float32)
    got = DotProd().apply({}, jnp.asarray(x), jnp.asarray(y))
    np.testing.assert_allclose(np.asarray(got), (x * y).sum(-1, keepdims=True),
                               rtol=1e-5)

    wk = rng.uniform(size=(3, 4)).astype(np.float32)
    mat = rng.normal(size=(3, 4, 5)).astype(np.float32)
    got = ConvexCombination().apply({}, jnp.asarray(wk), jnp.asarray(mat))
    np.testing.assert_allclose(np.asarray(got),
                               np.einsum("bk,bkd->bd", wk, mat), rtol=1e-5)
    # flat input form
    got2 = ConvexCombination().apply({}, jnp.asarray(wk),
                                     jnp.asarray(mat.reshape(3, 20)))
    np.testing.assert_allclose(np.asarray(got2), np.asarray(got), rtol=1e-6)

    got = CosSimVecMat().apply({}, jnp.asarray(x), jnp.asarray(mat))
    want = np.einsum("bd,bkd->bk", x, mat) / (
        np.linalg.norm(x, axis=-1, keepdims=True)
        * np.linalg.norm(mat, axis=-1) + 1e-12)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4)

    ids = jnp.asarray([[1, 2, 3], [3, 0, 3]])
    got = EosIdCheck(eos_id=3).apply({}, ids)
    np.testing.assert_array_equal(np.asarray(got),
                                  [[0, 0, 1], [1, 0, 1]])

    m = PRelu(channels=1, init_slope=0.1)
    p = m.init(jax.random.PRNGKey(0), jnp.asarray(x))
    got = np.asarray(m.apply(p, jnp.asarray(x)))
    np.testing.assert_allclose(got, np.where(x >= 0, x, 0.1 * x), rtol=1e-5)

    sp = ScalingProjection()
    p = sp.init(jax.random.PRNGKey(0), jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(sp.apply(p, jnp.asarray(x))), x,
                               rtol=1e-6)        # init scale = 1

    np.testing.assert_allclose(
        np.asarray(SliceProjection(1, 4).apply({}, jnp.asarray(x))),
        x[:, 1:4], rtol=1e-6)

    tp = TransposedFullMatrixProjection(7)
    p = tp.init(jax.random.PRNGKey(0), jnp.asarray(x))
    wmat = np.asarray(jax.tree_util.tree_leaves(p["params"])[0])
    np.testing.assert_allclose(np.asarray(tp.apply(p, jnp.asarray(x))),
                               x @ wmat.T, rtol=1e-5)

    nchw = rng.normal(size=(2, 3, 4, 5)).astype(np.float32)
    nhwc = np.asarray(SwitchOrder("NHWC").apply({}, jnp.asarray(nchw)))
    np.testing.assert_allclose(nhwc, nchw.transpose(0, 2, 3, 1))
    back = np.asarray(SwitchOrder("NCHW").apply({}, jnp.asarray(nhwc)))
    np.testing.assert_allclose(back, nchw)


def test_bilinear_interp_shapes_and_identity():
    from paddle_tpu.nn.layers import BilinearInterp
    x = jnp.asarray(np.random.RandomState(0).normal(
        size=(2, 4, 6, 3)).astype(np.float32))
    up = BilinearInterp(8, 12).apply({}, x)
    assert up.shape == (2, 8, 12, 3)
    same = BilinearInterp(4, 6).apply({}, x)
    np.testing.assert_allclose(np.asarray(same), np.asarray(x), atol=1e-6)


def test_max_pool_with_mask():
    from paddle_tpu.nn.layers import MaxPoolWithMask
    rng = np.random.RandomState(0)
    x = rng.normal(size=(2, 4, 4, 3)).astype(np.float32)
    pooled, mask = MaxPoolWithMask(window=2).apply({}, jnp.asarray(x))
    assert pooled.shape == (2, 2, 2, 3) and mask.shape == (2, 2, 2, 3)
    p, m = np.asarray(pooled), np.asarray(mask)
    for b in range(2):
        for i in range(2):
            for j in range(2):
                for c in range(3):
                    win = x[b, 2*i:2*i+2, 2*j:2*j+2, c]
                    assert p[b, i, j, c] == win.max()
                    fy, fx = divmod(int(m[b, i, j, c]), 4)
                    assert x[b, fy, fx, c] == win.max()
