"""Test harness: force an 8-device CPU platform so sharding/collective tests run
without TPU hardware — the analog of the reference's in-process localhost pserver
tests (``/root/reference/paddle/gserver/tests/test_CompareSparse.cpp:64``)."""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture
def nprng():
    return np.random.RandomState(0)
