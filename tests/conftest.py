"""Test harness: force an 8-device CPU platform so sharding/collective tests run
without TPU hardware — the analog of the reference's in-process localhost pserver
tests (``/root/reference/paddle/gserver/tests/test_CompareSparse.cpp:64``)."""

import os

# Must be set before jax is imported anywhere. Force-override: the shell env
# carries JAX_PLATFORMS=axon (the real TPU); tests must run on the virtual
# 8-device CPU platform for determinism and sharding coverage.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# sitecustomize.py (axon TPU plugin) imports jax at interpreter start, capturing
# JAX_PLATFORMS=axon before this file runs — override via config as well.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture
def nprng():
    return np.random.RandomState(0)
