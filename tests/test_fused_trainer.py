"""Fused train-step hot loop equivalence suite (ISSUE 1 tentpole).

The contract under test: ``Trainer(steps_per_call=K, grad_accum=M)`` runs
K optimizer steps per device dispatch, each accumulating M host-batch
microbatches (mean-of-means, weight-correct) — and reproduces K*M PLAIN
dispatches (one jitted grad call per microbatch + one jitted update per
optimizer step) bit-for-bit in f32: params, per-step losses, and evaluator
stats, with and without ``param_sharding`` and with weighted batches.
"""

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import optim, parallel
from paddle_tpu.core.module import Module
from paddle_tpu.nn import costs
from paddle_tpu.optim.optimizers import apply_updates
from paddle_tpu.train import Trainer, ClassificationError, events as ev


class MLP(Module):
    def __init__(self, hidden=32, classes=8):
        super().__init__()
        self.hidden = nn.Linear(hidden, act="relu", name="hidden")
        self.out = nn.Linear(classes, name="out")

    def forward(self, x, train=False):
        return self.out(self.hidden(x))


MLP_RULES = parallel.ShardingRules([
    ("*/hidden/w", P(None, "model")),
    ("*/hidden/b", P("model")),
    ("*/out/w", P("model", None)),
])


def _batches(n=8, bs=32, d=16, classes=8, seed=0, weighted=False):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        b = {"x": rng.normal(size=(bs, d)).astype(np.float32),
             "label": rng.randint(0, classes, bs).astype(np.int32)}
        if weighted:
            # includes zero weights: the mask-correctness case
            b["weight"] = rng.randint(0, 3, bs).astype(np.float32)
        out.append(b)
    return out


def _make_trainer(K, M, batches, mesh=None, param_sharding=None,
                  evaluator=None, optimizer=None, donate=True,
                  pipeline_depth=1, telemetry=None):
    tr = Trainer(
        model=MLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optimizer or optim.adam(1e-3),
        mesh=mesh, param_sharding=param_sharding, evaluator=evaluator,
        donate=donate, steps_per_call=K, grad_accum=M,
        pipeline_depth=pipeline_depth, telemetry=telemetry)
    tr.init(jax.random.PRNGKey(0), batches[0])
    return tr


def _run(tr, batches, num_passes=1, **kw):
    losses, metrics = [], []

    def handler(e):
        if isinstance(e, ev.EndIteration):
            losses.append(e.cost)
            metrics.append(dict(e.metrics))

    tr.train(lambda: iter(batches), num_passes=num_passes,
             event_handler=handler, log_period=0, **kw)
    return jax.device_get(tr.train_state.params), losses, metrics


def _assert_trees_equal(a, b):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def _plain_dispatch_reference(trainer_params, opt, batches, M, mesh=None,
                              shard=None):
    """K*M PLAIN steps: one jitted value_and_grad dispatch per microbatch,
    gradients accumulated in microbatch order, mean over M, one jitted
    optimizer update per accumulated step — the unfused execution of the
    fused pipeline's exact math."""
    model = MLP()

    def micro_loss(p, b):
        out = model.apply({"params": p}, b["x"])
        per_ex = costs.softmax_cross_entropy(out, b["label"])
        w = b.get("weight")
        if w is not None:
            return jnp.sum(per_ex * w) / jnp.maximum(jnp.sum(w), 1e-9)
        return jnp.mean(per_ex)

    vg = jax.jit(jax.value_and_grad(micro_loss))

    @jax.jit
    def update(grads, opt_state, params, step):
        updates, new_opt = opt.update(grads, opt_state, params, step)
        return apply_updates(params, updates), new_opt

    params = jax.tree_util.tree_map(jnp.asarray, trainer_params)
    opt_state = opt.init(params)
    step = jnp.zeros((), jnp.int32)
    losses = []
    for i in range(0, len(batches), M):
        group = batches[i:i + M]
        gacc = jax.tree_util.tree_map(jnp.zeros_like, params)
        lacc = jnp.zeros((), jnp.float32)
        for hb in group:
            b = (pt.core.mesh.shard_batch(mesh, hb) if shard
                 else jax.tree_util.tree_map(jnp.asarray, hb))
            loss, g = vg(params, b)
            gacc = jax.tree_util.tree_map(jnp.add, gacc, g)
            lacc = lacc + loss
        grads = jax.tree_util.tree_map(lambda g: g / len(group), gacc)
        losses.append(float(lacc / len(group)))
        params, opt_state = update(grads, opt_state, params, step)
        step = step + 1
    return jax.device_get(params), losses


def test_steps_per_call_matches_plain_bitexact():
    """K-fused dispatch == K plain Trainer dispatches, bit for bit in f32:
    params, loss trajectory, and evaluator stats."""
    batches = _batches(8)
    p1, l1, m1 = _run(_make_trainer(1, 1, batches,
                                    evaluator=ClassificationError()),
                      batches)
    p4, l4, m4 = _run(_make_trainer(4, 1, batches,
                                    evaluator=ClassificationError()),
                      batches)
    assert l1 == l4
    assert m1 == m4                 # per-step evaluator results identical
    _assert_trees_equal(p1, p4)


def test_grad_accum_matches_plain_dispatch_reference():
    """Trainer(steps_per_call=K, grad_accum=M) reproduces K*M plain steps
    (params + per-step losses) bit-for-bit in f32 — with WEIGHTED batches
    (zero weights included), so the mean-of-means accumulation is
    mask/weight-correct, not just unweighted-mean-correct."""
    batches = _batches(8, weighted=True)
    opt = optim.adam(1e-3)
    tr = _make_trainer(2, 2, batches, optimizer=opt)
    p0 = jax.device_get(tr.train_state.params)
    # the reference consumes batches sharded over the SAME data-parallel
    # mesh (bit-exactness holds per layout; cross-device-count reduction
    # order differs, which is what test_single_vs_multichip tolerates)
    fused_p, fused_l, _ = _run(tr, batches)
    ref_p, ref_l = _plain_dispatch_reference(p0, opt, batches, M=2,
                                             mesh=tr.mesh, shard=True)
    assert fused_l == ref_l
    _assert_trees_equal(fused_p, ref_p)
    assert int(tr.train_state.step) == 4        # 8 batches / M=2 steps


def test_fused_matches_plain_dispatch_with_param_sharding():
    """The same bit-exact contract with model-parallel ``param_sharding``
    set: the fused accumulation composes with the sharded layout (grad
    collectives per accumulated step inside the compiled scan) and the
    layout survives the fused dispatch."""
    batches = _batches(8, weighted=True)
    mesh = pt.make_mesh({"data": 2, "model": 4})
    opt = optim.adam(1e-3)
    tr = _make_trainer(2, 2, batches, mesh=mesh, param_sharding=MLP_RULES,
                       optimizer=opt)
    p0 = jax.device_get(tr.train_state.params)

    # reference params must live in the SAME committed sharded layout
    ref_tr = _make_trainer(1, 1, batches, mesh=mesh,
                           param_sharding=MLP_RULES, optimizer=opt)
    fused_p, fused_l, _ = _run(tr, batches)
    ref_p, ref_l = _plain_dispatch_reference(
        ref_tr.train_state.params, opt, batches, M=2, mesh=mesh, shard=True)
    assert fused_l == ref_l
    _assert_trees_equal(fused_p, ref_p)
    root = next(iter(tr.train_state.params))
    w = tr.train_state.params[root]["hidden"]["w"]
    assert tuple(w.sharding.spec) == (None, "model")


def test_fused_donation_safety():
    """donate=True (default): event handlers may read trainer.train_state
    after every fused call — the refreshed buffers must be live (donation
    invalidated the previous ones), across multiple passes."""
    batches = _batches(8)
    tr = _make_trainer(2, 2, batches, donate=True)
    norms = []

    def handler(e):
        if isinstance(e, ev.EndIteration):
            norms.append(float(jax.device_get(
                optim.global_norm(tr.train_state.params))))

    tr.train(lambda: iter(batches), num_passes=2, event_handler=handler,
             log_period=0)
    # 2 passes x (8 batches / M=2) = 8 optimizer steps
    assert len(norms) == 8 and all(np.isfinite(n) for n in norms)
    assert int(tr.train_state.step) == 8


def test_fused_tail_smaller_than_group():
    """A pass whose batch count doesn't divide K*M flushes the tail: full
    K x M dispatch, then the leftovers (the final step averaging over < M
    microbatches). 7 batches at K=2, M=2 -> steps of 2+2, 2, 1
    microbatches = 4 optimizer steps — and EndIteration step numbers stay
    monotonic 1..4 even though the flush splits into several dispatches."""
    batches = _batches(7)
    tr = _make_trainer(2, 2, batches)
    steps = []

    def handler(e):
        if isinstance(e, ev.EndIteration):
            steps.append(e.step)

    tr.train(lambda: iter(batches), num_passes=1, event_handler=handler,
             log_period=0)
    assert steps == [1, 2, 3, 4]
    assert int(tr.train_state.step) == 4


def test_fused_resume_mid_pass_reproduces_uninterrupted(tmp_path):
    """Kill mid-pass after a fused-call-boundary checkpoint, resume with the
    same (K, M): the replayed grouping realigns and the final params equal
    the uninterrupted fused run's, bit for bit."""
    batches = _batches(16)

    def make():
        return _make_trainer(2, 2, batches)

    tr_a = make()
    p_want, _, _ = _run(tr_a, batches, num_passes=2)
    want_step = int(tr_a.train_state.step)

    class Killed(Exception):
        pass

    def killer(e):
        # dies after the second fused call of pass 1 (batch 8 = a
        # saving_period=8 checkpoint boundary)
        if isinstance(e, ev.EndIteration) and e.pass_id == 1 \
                and e.batch_id == 7:
            raise Killed()

    tr_b = make()
    with pytest.raises(Killed):
        tr_b.train(lambda: iter(batches), num_passes=2,
                   checkpoint_dir=str(tmp_path), saving_period=8,
                   log_period=0, event_handler=killer)

    tr_c = _make_trainer(2, 2, batches)   # fresh trainer, same config
    tr_c.train(lambda: iter(batches), num_passes=2,
               checkpoint_dir=str(tmp_path), saving_period=8,
               log_period=0, resume=True)
    assert int(tr_c.train_state.step) == want_step
    _assert_trees_equal(p_want, jax.device_get(tr_c.train_state.params))


def test_fused_evaluator_counts_match_plain():
    """ClassificationError accumulated through the stacked [K, M] stats
    equals the plain per-batch accumulation (stats ride the compiled scan
    and replay on host in order)."""
    batches = _batches(8)
    ev1 = ClassificationError()
    ev2 = ClassificationError()
    _run(_make_trainer(1, 1, batches, evaluator=ev1), batches)
    _run(_make_trainer(4, 2, batches, evaluator=ev2), batches)
    assert ev1._total == ev2._total
    # different step grouping -> different trajectories, but pass totals
    # count every example exactly once
    assert ev1._total == 8 * 32


# ------------------------------------------------- async host pipeline

def _run_events(tr, batches, num_passes=1, **kw):
    """Like _run but returns the FULL event sequence (order included)."""
    events = []
    tr.train(lambda: iter(batches), num_passes=num_passes,
             event_handler=events.append, log_period=0, **kw)
    return jax.device_get(tr.train_state.params), events


def test_pipelined_fused_bitexact_and_event_order():
    """pipeline_depth=W (stager thread + bounded in-flight window +
    deferred FIFO drain) reproduces the serial fused run bit for bit:
    params in f32, per-step costs, evaluator metrics, and the FULL event
    sequence in the exact serial order — including a ragged pass tail
    (13 batches at K=2, M=2) over two passes."""
    batches = _batches(13)
    p1, e1 = _run_events(_make_trainer(2, 2, batches,
                                       evaluator=ClassificationError()),
                         batches, num_passes=2)
    p3, e3 = _run_events(_make_trainer(2, 2, batches,
                                       evaluator=ClassificationError(),
                                       pipeline_depth=3),
                         batches, num_passes=2)
    assert e1 == e3                 # events are dataclasses: order + fields
    _assert_trees_equal(p1, p3)


def test_pipelined_mid_pass_checkpoint_resume(tmp_path):
    """The kill/resume contract under pipelining: a checkpoint boundary
    forces a full drain (the save observes a quiesced train_state), so a
    mid-pass kill after the boundary save resumes to the SAME final params
    as the uninterrupted SERIAL run."""
    batches = _batches(16)
    tr_a = _make_trainer(2, 2, batches)            # serial reference
    p_want, _, _ = _run(tr_a, batches, num_passes=2)
    want_step = int(tr_a.train_state.step)

    class Killed(Exception):
        pass

    def killer(e):
        if isinstance(e, ev.EndIteration) and e.pass_id == 1 \
                and e.batch_id == 7:
            raise Killed()

    tr_b = _make_trainer(2, 2, batches, pipeline_depth=2)
    with pytest.raises(Killed):
        tr_b.train(lambda: iter(batches), num_passes=2,
                   checkpoint_dir=str(tmp_path), saving_period=8,
                   log_period=0, event_handler=killer)

    tr_c = _make_trainer(2, 2, batches, pipeline_depth=2)
    tr_c.train(lambda: iter(batches), num_passes=2,
               checkpoint_dir=str(tmp_path), saving_period=8,
               log_period=0, resume=True)
    assert int(tr_c.train_state.step) == want_step
    _assert_trees_equal(p_want, jax.device_get(tr_c.train_state.params))


def test_pipelined_saving_period_event_order_matches_serial(tmp_path):
    """With mid-pass saving_period checkpoints, the pipelined event
    sequence (drains forced at boundaries) still equals the serial one,
    and both runs end bit-identical."""
    batches = _batches(12)
    p1, e1 = _run_events(_make_trainer(2, 2, batches), batches,
                         checkpoint_dir=str(tmp_path / "serial"),
                         saving_period=4)
    p2, e2 = _run_events(_make_trainer(2, 2, batches, pipeline_depth=4),
                         batches, checkpoint_dir=str(tmp_path / "piped"),
                         saving_period=4)
    assert e1 == e2
    _assert_trees_equal(p1, p2)


def test_plain_loop_deferred_fetch_matches_serial(tmp_path):
    """K=1, M=1 with pipeline_depth=2 (the deferred-fetch window; nan_check
    off) reproduces the serial plain loop bit for bit: params, costs,
    evaluator metrics, events, and the mid-pass checkpoint stream."""
    batches = _batches(9)
    p1, e1 = _run_events(_make_trainer(1, 1, batches,
                                       evaluator=ClassificationError()),
                         batches, num_passes=2,
                         checkpoint_dir=str(tmp_path / "serial"),
                         saving_period=4)
    p2, e2 = _run_events(_make_trainer(1, 1, batches,
                                       evaluator=ClassificationError(),
                                       pipeline_depth=2),
                         batches, num_passes=2,
                         checkpoint_dir=str(tmp_path / "piped"),
                         saving_period=4)
    assert e1 == e2
    _assert_trees_equal(p1, p2)


def test_plain_nan_check_stays_serial_and_raises():
    """nan_check needs the loss on host before the next dispatch, so the
    plain loop ignores pipeline_depth with it on — and still raises at the
    poisoned batch."""
    batches = _batches(4)
    batches[2]["x"][:] = np.nan
    tr = Trainer(
        model=MLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3), nan_check=True, pipeline_depth=4)
    tr.init(jax.random.PRNGKey(0), batches[0])
    with pytest.raises(FloatingPointError, match="batch 2"):
        tr.train(lambda: iter(batches), num_passes=1, log_period=0)


def test_pipelined_nan_check_skips_poisoned_save(tmp_path):
    """nan_check + pipelining: a non-finite loss anywhere in a group still
    SKIPS the boundary save (never persist a poisoned train_state) and the
    replay raises."""
    from paddle_tpu.train import checkpoint as ckpt_lib
    batches = _batches(8)
    batches[5]["x"][:] = np.nan          # poisons the second group
    tr = Trainer(
        model=MLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3), steps_per_call=2, grad_accum=2,
        nan_check=True, pipeline_depth=2)
    tr.init(jax.random.PRNGKey(0), batches[0])
    with pytest.raises(FloatingPointError):
        tr.train(lambda: iter(batches), num_passes=1, log_period=0,
                 checkpoint_dir=str(tmp_path), saving_period=8)
    # the batch-8 boundary save covered the poisoned group: skipped
    assert ckpt_lib.latest_pass(str(tmp_path)) is None


def test_pipelined_stager_thread_always_closed():
    """The stager thread dies with the pass — on clean completion AND when
    a handler raises mid-pass (the try/finally close path)."""
    import threading

    def stager_threads():
        return [t for t in threading.enumerate()
                if t.name == "paddle_tpu.host_pipeline.stager"]

    batches = _batches(8)
    tr = _make_trainer(2, 2, batches, pipeline_depth=2)
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert not stager_threads()

    class Boom(Exception):
        pass

    tr2 = _make_trainer(2, 2, batches, pipeline_depth=2)

    def bomb(e):
        if isinstance(e, ev.EndIteration):
            raise Boom()

    with pytest.raises(Boom):
        tr2.train(lambda: iter(batches), num_passes=1, log_period=0,
                  event_handler=bomb)
    deadline = time.time() + 5.0
    while stager_threads() and time.time() < deadline:
        time.sleep(0.02)
    assert not stager_threads()


def test_pipelined_telemetry_overlap_accounting():
    """Pipelined fused runs record the overlap keys (stage_ms /
    drain_wait_ms / overlap_frac all non-None, device_ms None, fenced
    False — no per-call fence), serial runs carry them as None, and
    telemetry does not perturb the pipelined math."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    batches = _batches(8)
    p_serial, l_serial, _ = _run(_make_trainer(2, 2, batches), batches)

    mem = InMemorySink()
    tr = _make_trainer(2, 2, batches, pipeline_depth=2,
                       telemetry=Telemetry(sinks=[mem]))
    p_piped, l_piped, _ = _run(tr, batches)
    assert l_piped == l_serial
    _assert_trees_equal(p_serial, p_piped)
    steps = mem.by_kind("step")
    assert len(steps) == 2                      # 8 batches / (K=2 * M=2)
    for r in steps:
        assert r["stage_ms"] is not None and r["stage_ms"] >= 0
        assert r["drain_wait_ms"] is not None and r["drain_wait_ms"] >= 0
        assert r["overlap_frac"] is not None and 0 <= r["overlap_frac"] <= 1
        assert r["device_ms"] is None and r["fenced"] is False
        assert r["grad_norm"] is not None       # health still rides along

    mem2 = InMemorySink()
    tr2 = _make_trainer(2, 2, batches, telemetry=Telemetry(sinks=[mem2]))
    _run(tr2, batches)
    for r in mem2.by_kind("step"):              # serial: keys fixed, None
        assert r["stage_ms"] is None and r["drain_wait_ms"] is None
        assert r["overlap_frac"] is None


# ---------------------------------------------------------------- remat

def test_transformer_remat_scan_matches_plain():
    """TransformerLM(remat=...) — the block stack as jax.checkpoint'd
    lax.scan over stacked layer params — matches the plain unrolled stack
    on the SAME variables tree (logits and grads; scan/remat appear in the
    jaxpr). Bit-exactness is not required across the scan boundary (XLA
    refuses nothing, but fusion differs); 1e-5 absolute on unit-scale
    logits is last-bits."""
    V, D, T, B = 64, 32, 16, 4
    kw = dict(vocab=V, dim=D, num_layers=3, num_heads=4, ffn_hidden=64,
              max_len=T)
    from paddle_tpu.models import TransformerLM
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, V, (B, T)), jnp.int32)
    tgt = jnp.asarray(rng.randint(0, V, (B, T)), jnp.int32)
    base = TransformerLM(**kw)
    variables = base.init(jax.random.PRNGKey(0), ids)

    for policy in ("dots", "full"):
        rem = TransformerLM(**kw, remat=policy)
        lg0 = np.asarray(base.apply(variables, ids))
        lg1 = np.asarray(rem.apply(variables, ids))
        np.testing.assert_allclose(lg0, lg1, rtol=1e-5, atol=1e-5)

        def loss(m):
            def f(p):
                lg = m.apply({"params": p}, ids)
                return jnp.mean(costs.softmax_cross_entropy(
                    lg.reshape(-1, V), tgt.reshape(-1)))
            return f

        g0 = jax.jit(jax.grad(loss(base)))(variables["params"])
        g1 = jax.jit(jax.grad(loss(rem)))(variables["params"])
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    rem = TransformerLM(**kw, remat="dots")
    jaxpr = str(jax.make_jaxpr(
        lambda p: rem.apply({"params": p}, ids))(variables["params"]))
    assert "scan[" in jaxpr, "remat path must run the stack as lax.scan"
    assert "remat" in jaxpr or "checkpoint" in jaxpr, \
        "remat path must wrap the scan body in jax.checkpoint"
    # init under the remat config builds the IDENTICAL per-block tree
    # (checkpoints move freely between remat and plain configs)
    v2 = TransformerLM(**kw, remat="dots").init(jax.random.PRNGKey(0), ids)
    _assert_trees_equal(jax.device_get(variables), jax.device_get(v2))


def test_remat_model_trains_under_fused_trainer():
    """The full composition: remat scan-over-layers model + steps_per_call
    + grad_accum in one compiled pipeline, vs the same model unfused —
    identical final params (tight f32 tolerance; the remat scan body
    compiles once per K-step scan so the math matches exactly across K)."""
    from paddle_tpu.models import TransformerLM
    V, T, bs = 64, 16, 8
    rng = np.random.RandomState(0)
    batches = [{"x": rng.randint(0, V, (bs, T)).astype(np.int32),
                "y": rng.randint(0, V, (bs, T)).astype(np.int32)}
               for _ in range(8)]

    def make(K, M):
        tr = Trainer(
            model=TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                                ffn_hidden=64, max_len=T, remat="dots"),
            loss_fn=lambda out, b: costs.softmax_cross_entropy(
                out.reshape(-1, V), b["y"].reshape(-1)),
            optimizer=optim.adam(1e-3), steps_per_call=K, grad_accum=M)
        tr.init(jax.random.PRNGKey(0), batches[0])
        return tr

    p_fused, l_fused, _ = _run(make(4, 2), batches)
    p_plain, l_plain, _ = _run(make(1, 2), batches)
    assert l_fused == l_plain
    _assert_trees_equal(p_fused, p_plain)
