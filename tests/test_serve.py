"""Serving runtime tests (ISSUE 9): paged KV cache correctness, the
decode-shaped Pallas kernel vs its oracle, engine/scheduler behavior, and
the two acceptance contracts —

- **KV correctness**: prefill + N x decode_step logits BIT-EQUAL (f32,
  CPU) to the full-sequence forward, for ragged lengths crossing block
  boundaries; and block free/reuse reproduces identical tokens after
  eviction churn (stale pool contents must be fully masked).
- **The no-retrace invariant**: one compiled program per entry point
  across arbitrary admission/eviction churn.
"""

import logging
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models import TransformerLM
from paddle_tpu.serve import (BlockAllocator, ContinuousBatchingScheduler,
                              DecodeEngine, PagedKVCache)
from paddle_tpu.serve import kv_cache as kvc

V, W, DIM, LAYERS, HEADS, FFN = 64, 24, 32, 2, 4, 64
BS, MB = 4, 6                        # block_size x max_blocks = W


@pytest.fixture(scope="module")
def model_and_vars():
    model = TransformerLM(vocab=V, dim=DIM, num_layers=LAYERS,
                          num_heads=HEADS, ffn_hidden=FFN, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))
    return model, vs


def _greedy_oracle(model, vs, prompt, n_new):
    """Token-by-token greedy decode through the full training forward."""
    fwd = jax.jit(lambda v, i: model.apply(v, i))
    seq, out = list(prompt), []
    for _ in range(n_new):
        pad = np.zeros((1, W), np.int32)
        pad[0, :len(seq)] = seq
        logits = fwd(vs, jnp.asarray(pad))
        tok = int(np.argmax(np.asarray(logits[0, len(seq) - 1])))
        out.append(tok)
        seq.append(tok)
    return out


# ---------------------------------------------------------------------------
# kv_cache: allocator + pure gather/scatter
# ---------------------------------------------------------------------------

def test_block_allocator_invariants():
    a = BlockAllocator(6)                    # blocks 1..5 usable
    assert a.num_free == 5
    got = a.alloc(3)
    assert got == [1, 2, 3] and a.num_free == 2
    assert a.alloc(3) is None and a.num_free == 2   # refuse, no change
    a.free([2])
    assert a.alloc(3) == [4, 5, 2]           # FIFO reuse
    with pytest.raises(AssertionError):
        a.free([kvc.NULL_BLOCK])


def test_cache_capacity_and_free(nprng):
    c = PagedKVCache(num_layers=1, num_heads=2, head_dim=4, num_blocks=5,
                     block_size=BS, max_slots=2, max_blocks_per_seq=MB)
    assert c.context_width == MB * BS
    assert c.ensure_capacity(0, 9)           # 3 blocks
    assert c.free_blocks == 1
    assert not c.ensure_capacity(1, 9)       # needs 3, 1 free: refuse
    assert c.free_blocks == 1                # refusal changed nothing
    assert c.ensure_capacity(1, 3)           # 1 block fits
    c.free_slot(0)
    assert c.free_blocks == 3
    assert (c.tables[0] == kvc.NULL_BLOCK).all() and c.lengths[0] == 0


def test_gather_scatter_roundtrip(nprng):
    H, hd = 2, 4
    pages = jnp.zeros((8, BS, H, hd), jnp.float32)
    table = jnp.asarray([[3, 1, 5, 0, 0, 0]], jnp.int32)
    kv = jnp.asarray(nprng.randn(1, MB * BS, H, hd).astype(np.float32))
    length = jnp.asarray([9], jnp.int32)
    pages = kvc.scatter_prefill(pages, kv, table, length)
    got = kvc.gather_pages(pages, table)
    np.testing.assert_array_equal(np.asarray(got[0, :9]),
                                  np.asarray(kv[0, :9]))
    # rows >= length went to the null block, not the sequence's pages:
    # row 8 is block 5 offset 0, so block 5's tail stays untouched
    assert not np.any(np.asarray(pages[5][1:]))

    tok = jnp.asarray(nprng.randn(1, H, hd).astype(np.float32))
    pages = kvc.scatter_token(pages, tok, table, jnp.asarray([9]),
                              jnp.asarray([True]))
    got = kvc.gather_pages(pages, table)
    np.testing.assert_array_equal(np.asarray(got[0, 9]), np.asarray(tok[0]))
    # inactive slots scatter to the null block only
    before = np.asarray(pages)
    pages2 = kvc.scatter_token(pages, tok * 7, table, jnp.asarray([9]),
                               jnp.asarray([False]))
    after = np.asarray(pages2)
    np.testing.assert_array_equal(before[1:], after[1:])


# ---------------------------------------------------------------------------
# the decode-shaped Pallas kernel vs its oracle
# ---------------------------------------------------------------------------

def test_paged_decode_attention_matches_reference(nprng):
    from paddle_tpu.nn.pallas_attention import (paged_decode_attention,
                                                paged_reference_attention)
    S, H, D, N = 4, 2, 16, 32
    q = jnp.asarray(nprng.randn(S, H, D).astype(np.float32))
    pk = jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32))
    pv = jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32))
    tables = jnp.asarray(nprng.randint(0, N, (S, MB)), jnp.int32)
    # ragged: mid-block, inactive, full capacity, block-boundary
    lengths = jnp.asarray([5, 0, MB * BS, 12], jnp.int32)
    out = paged_decode_attention(q, pk, pv, tables, lengths)
    ref = paged_reference_attention(q, pk, pv, tables, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)
    assert not np.any(np.asarray(out[1]))    # inactive slot: zeros


def test_model_decode_step_paged_impl_matches_xla(model_and_vars, nprng):
    """The Pallas paged path and the bit-exact XLA gather path agree
    (allclose — different softmax algebra) on the same cache state."""
    model, vs = model_and_vars
    hd = DIM // HEADS
    cache = PagedKVCache(LAYERS, HEADS, hd, 16, BS, max_slots=2,
                         max_blocks_per_seq=MB)
    ids = nprng.randint(0, V, (2, W)).astype(np.int32)
    _, (ks, vsv) = jax.jit(
        lambda v, i: model.apply(v, i, method="prefill"))(
            vs, jnp.asarray(ids))
    for b in range(2):
        assert cache.ensure_capacity(b, 10)
    tbl = jnp.asarray(cache.tables)
    plen = jnp.asarray([9, 6], jnp.int32)
    scat = jax.vmap(kvc.scatter_prefill, in_axes=(0, 0, None, None))
    cache.k = scat(cache.k, ks, tbl, plen)
    cache.v = scat(cache.v, vsv, tbl, plen)
    tok = jnp.asarray([3, 7], jnp.int32)
    act = jnp.asarray([True, False])      # one inactive lane
    outs = {}
    for impl in ("xla", "paged"):
        logits, _ = model.apply(vs, tok, (cache.k, cache.v, tbl), plen,
                                act, attn_impl=impl, method="decode_step")
        outs[impl] = np.asarray(logits)
    # both impls agree on the active lane AND on the inactive lane's
    # zero-context convention (the whole [S] front, not just active rows)
    np.testing.assert_allclose(outs["paged"], outs["xla"],
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# acceptance: prefill + N x decode_step BIT-EQUAL to the full forward
# ---------------------------------------------------------------------------

def test_prefill_decode_bit_equal_full_forward(model_and_vars, nprng):
    """f32 CPU: for ragged lengths crossing block boundaries, every
    decoded position's logits are bitwise identical to the full-sequence
    forward at the fixed padded width — the serving path introduces ZERO
    numeric drift over the training forward."""
    model, vs = model_and_vars
    B = 3
    lens = [13, W, 7]                 # mid-block, full, block-boundary+3
    P = 3                             # prefill length (rest decoded)
    ids = nprng.randint(0, V, (B, W)).astype(np.int32)
    oracle = np.asarray(jax.jit(lambda v, i: model.apply(v, i))(
        vs, jnp.asarray(ids)))

    hd = DIM // HEADS
    cache = PagedKVCache(LAYERS, HEADS, hd, B * MB + 1, BS, max_slots=B,
                         max_blocks_per_seq=MB)
    logits_pre, (ks, vsv) = jax.jit(
        lambda v, i: model.apply(v, i, method="prefill"))(
            vs, jnp.asarray(ids))
    # prefill logits themselves are bit-equal to forward
    np.testing.assert_array_equal(np.asarray(logits_pre), oracle)

    for b in range(B):
        assert cache.ensure_capacity(b, lens[b])
    tbl = jnp.asarray(cache.tables)
    plen = jnp.full((B,), P, jnp.int32)
    scat = jax.vmap(kvc.scatter_prefill, in_axes=(0, 0, None, None))
    cache.k = scat(cache.k, ks, tbl, plen)
    cache.v = scat(cache.v, vsv, tbl, plen)

    decode = jax.jit(lambda v, t, kv, pos, a: model.apply(
        v, t, kv, pos, a, method="decode_step"))
    for t in range(P, max(lens)):
        active = jnp.asarray([t < lens[b] for b in range(B)])
        pos = jnp.full((B,), t, jnp.int32)
        logits, (cache.k, cache.v, _) = decode(
            vs, jnp.asarray(ids[:, t]), (cache.k, cache.v, tbl), pos,
            active)
        for b in range(B):
            if t < lens[b]:
                np.testing.assert_array_equal(
                    np.asarray(logits[b]), oracle[b, t],
                    err_msg=f"slot {b} position {t}")


def test_block_free_reuse_identical_after_churn(model_and_vars, nprng):
    """Evicting sequences and re-admitting onto RECYCLED blocks (stale
    pool contents) reproduces the exact same generation — proof the
    length mask fully owns the block-content boundary."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       num_blocks=2 * MB + 1)
    prompt = list(nprng.randint(0, V, 5))
    sched = ContinuousBatchingScheduler(eng)
    first = sched.submit(prompt, 6)
    sched.run()
    assert first.done

    # churn: fill and free the pool with other sequences several times
    for i in range(3):
        s2 = ContinuousBatchingScheduler(eng)
        for j in range(3):
            s2.submit(list(nprng.randint(0, V, 4 + i + j)), 5 + j)
        s2.run()
    assert eng.cache.free_blocks == 2 * MB   # all returned

    again = ContinuousBatchingScheduler(eng)
    rerun = again.submit(prompt, 6)
    again.run()
    assert rerun.tokens == first.tokens      # bit-identical generation
    # and the whole time, nothing ever retraced
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}


# ---------------------------------------------------------------------------
# engine + scheduler
# ---------------------------------------------------------------------------

def test_continuous_batching_completes_and_matches_oracle(model_and_vars,
                                                          nprng):
    from paddle_tpu.obs import InMemorySink, Telemetry
    model, vs = model_and_vars
    mem = InMemorySink()
    eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                       telemetry=Telemetry(sinks=[mem]))
    sched = ContinuousBatchingScheduler(eng)
    prompts = [list(nprng.randint(0, V, nprng.randint(2, 8)))
               for _ in range(8)]
    maxnew = [3, 9, 5, 12, 7, 4, 10, 6]
    reqs = [sched.submit(p, m) for p, m in zip(prompts, maxnew)]
    done = sched.run()
    assert len(done) == 8 and all(r.done for r in reqs)
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}
    # per-request telemetry: one record each, with the SLO fields
    recs = mem.by_kind("request")
    assert len(recs) == 8
    for r in recs:
        assert r["ttft_ms"] is not None and r["ttft_ms"] >= 0
        assert r["new_tokens"] >= 1
        if r["new_tokens"] >= 2:
            assert r["tpot_ms"] is not None and r["tpot_ms"] >= 0
    assert len(mem.by_kind("decode_tick")) == eng.ticks
    # generated tokens match the naive greedy full-forward oracle
    for req, p, m in list(zip(reqs, prompts, maxnew))[:3]:
        assert req.tokens == _greedy_oracle(model, vs, p, m)


def test_static_policy_gangs_and_is_slower(model_and_vars, nprng):
    """The gang baseline completes but burns idle-lane ticks on ragged
    lengths — the differential continuous batching exists to win."""
    model, vs = model_and_vars
    prompts = [list(nprng.randint(0, V, 4)) for _ in range(8)]
    maxnew = [2, 12, 2, 2, 12, 2, 2, 2]      # stragglers pin their gang
    ticks = {}
    for policy in ("continuous", "static"):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=BS)
        sched = ContinuousBatchingScheduler(eng, policy=policy)
        reqs = [sched.submit(p, m) for p, m in zip(prompts, maxnew)]
        sched.run()
        assert all(r.done for r in reqs)
        ticks[policy] = eng.ticks
    assert ticks["static"] > ticks["continuous"]


def test_pool_backpressure_defers_admission(model_and_vars, nprng):
    """A pool sized for ~2 concurrent sequences serves 4 requests by
    deferring admissions until eviction frees blocks."""
    model, vs = model_and_vars
    # 2 sequences x 3 blocks each fit; the third admission must wait
    eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                       num_blocks=2 * 3 + 1)
    sched = ContinuousBatchingScheduler(eng)
    reqs = [sched.submit(list(nprng.randint(0, V, 5)), 6)
            for _ in range(4)]
    done = sched.run()
    assert len(done) == 4 and all(r.done for r in reqs)
    assert eng.cache.free_blocks == 6
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}


class _FakeClock:
    """Deterministic scheduler clock: the test advances it between
    ticks, so deadline expiry is exact, not wall-time-flaky."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_deadline_evicts_running_slot_and_frees_blocks(model_and_vars,
                                                       nprng):
    """ISSUE 10: a slot that exceeds its deadline_s is evicted between
    ticks with finish_reason="timeout" and its blocks freed — a stuck/
    long request can no longer hold a slot + reservation forever."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    model, vs = model_and_vars
    mem = InMemorySink()
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       telemetry=Telemetry(sinks=[mem]))
    clock = _FakeClock()
    sched = ContinuousBatchingScheduler(eng, clock=clock)
    free0 = eng.cache.free_blocks
    stuck = sched.submit(list(nprng.randint(0, V, 4)), 18, deadline_s=2.5)
    quick = sched.submit(list(nprng.randint(0, V, 4)), 3)
    while sched.step():
        clock.t += 1.0                        # one "second" per tick
    assert quick.done and quick.finish_reason == "length"
    assert stuck.done and stuck.finish_reason == "timeout"
    # evicted mid-decode: partial tokens, well short of max_new
    assert 1 <= len(stuck.tokens) < 18
    # the whole reservation came back to the pool
    assert eng.cache.free_blocks == free0
    assert not eng.active.any()
    # surfaced in the request telemetry records
    recs = {r["rid"]: r for r in mem.by_kind("request")}
    assert recs[stuck.rid]["finish_reason"] == "timeout"
    assert recs[stuck.rid]["deadline_s"] == 2.5
    assert recs[quick.rid]["finish_reason"] == "length"
    assert recs[quick.rid]["deadline_s"] is None


def test_deadline_drops_expired_queued_request(model_and_vars, nprng):
    """A request whose deadline expires while still QUEUED (pool/slot
    backpressure) is dropped before ever taking a slot."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=1, block_size=BS)
    clock = _FakeClock()
    sched = ContinuousBatchingScheduler(eng, clock=clock)
    free0 = eng.cache.free_blocks
    long_req = sched.submit(list(nprng.randint(0, V, 4)), 10)
    starved = sched.submit(list(nprng.randint(0, V, 4)), 4, deadline_s=3.0)
    while sched.step():
        clock.t += 1.0
    assert long_req.finish_reason == "length"
    assert starved.finish_reason == "timeout"
    assert starved.slot is None and starved.tokens == []
    # the timed-out request never took a slot or any blocks
    assert eng.cache.free_blocks == free0


def test_deadline_evictions_emit_records_and_return_blocks(model_and_vars,
                                                           nprng):
    """ISSUE 11 satellite: BOTH deadline-eviction paths are visible in
    telemetry — the queued drop emits a kind="evict" record (previously
    only slot evictions were distinguishable), and the running slot's
    exact block ids land back on the BlockAllocator free list (leak
    regression)."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    model, vs = model_and_vars
    mem = InMemorySink()
    eng = DecodeEngine(model, vs, max_slots=1, block_size=BS,
                       telemetry=Telemetry(sinks=[mem]))
    clock = _FakeClock()
    sched = ContinuousBatchingScheduler(eng, clock=clock)
    running = sched.submit(list(nprng.randint(0, V, 4)), 18,
                           deadline_s=2.5)
    starved = sched.submit(list(nprng.randint(0, V, 4)), 4,
                           deadline_s=2.0)   # expires before a slot frees
    sched.step()                      # admit `running`; blocks reserved
    owned = list(eng.cache._owned[running.slot])
    assert owned, "admission reserved no blocks"
    while sched.step():
        clock.t += 1.0
    assert running.finish_reason == "timeout"
    assert starved.finish_reason == "timeout" and starved.slot is None
    # the evicted slot's block ids are ON the free list, not just counted
    assert set(owned) <= set(eng.cache.allocator._free)
    assert eng.cache.free_blocks == eng.cache.num_blocks - 1
    evicts = {r["rid"]: r for r in mem.by_kind("evict")}
    assert evicts[running.rid]["where"] == "running"
    assert evicts[running.rid]["blocks_freed"] == len(owned)
    assert evicts[starved.rid]["where"] == "queued"
    assert evicts[starved.rid]["blocks_freed"] == 0


def test_scheduler_surfaces_structured_backpressure(model_and_vars,
                                                    nprng):
    """ISSUE 11 satellite: when admission stalls on the pool, the
    scheduler records WHY (blocks vs slots) so a router doesn't guess."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                       num_blocks=2 * 3 + 1)
    sched = ContinuousBatchingScheduler(eng)
    for _ in range(4):
        sched.submit(list(nprng.randint(0, V, 5)), 6)
    sched.step()
    assert sched.last_backpressure == "blocks"    # pool, not slots
    sched.run()
    assert sched.last_backpressure is None        # cleared when flowing
    # the static gang-wait path clears it too (no stale reason while
    # the gang runs)
    eng2 = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    s2 = ContinuousBatchingScheduler(eng2, policy="static")
    for _ in range(2):
        s2.submit(list(nprng.randint(0, V, 5)), 4)
    s2.step()
    s2.last_backpressure = "blocks"               # simulate a stale read
    s2.step()                                     # gang still running
    assert s2.last_backpressure is None


def test_deadline_none_is_unchanged_and_validation(model_and_vars, nprng):
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    sched = ContinuousBatchingScheduler(eng)
    with pytest.raises(ValueError, match="deadline_s"):
        sched.submit([1, 2], 2, deadline_s=-1.0)
    req = sched.submit(list(nprng.randint(0, V, 3)), 4)
    sched.run()
    assert req.finish_reason == "length" and len(req.tokens) == 4


def test_decode_past_reservation_raises(model_and_vars):
    """Out-decoding the admission reservation must fail loud, not scatter
    new-token KV into the null block (silent wrong logits)."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    eng.admit(0, [1, 2, 3])                  # reserves 1 block (3 tokens)
    eng.decode_tick()                        # position 3 fills block 0
    with pytest.raises(RuntimeError, match="past its reservation"):
        eng.decode_tick()                    # position 4 needs block 2


def test_prompt_capacity_validation(model_and_vars):
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    sched = ContinuousBatchingScheduler(eng)
    with pytest.raises(ValueError, match="exceeds slot capacity"):
        sched.submit(list(range(W)), 2)      # W + 2 > capacity W
    with pytest.raises(ValueError, match="max_new_tokens"):
        sched.submit([1, 2], 0)
    with pytest.raises(ValueError, match="attention"):
        DecodeEngine(model, vs, attention="nope")


# ---------------------------------------------------------------------------
# inference.py routing satellites
# ---------------------------------------------------------------------------

def test_inference_predict_routes_serving_methods(tmp_path, model_and_vars,
                                                  nprng):
    from paddle_tpu.inference import export, load_inference_model
    model, vs = model_and_vars
    path = os.path.join(str(tmp_path), "bundle")
    export(path, model, vs)
    im = load_inference_model(path)
    prompts = [[1, 2, 3], [5, 6, 7, 8]]
    first = im.predict(prompts, method="prefill", max_slots=2,
                       block_size=BS)
    assert first.shape == (2,)
    # decode well past the prompts' first block: the session reserves
    # full slot capacity at prefill, so crossing block boundaries keeps
    # matching the greedy full-forward oracle (regression: an
    # under-reserved session silently scattered KV to the null block)
    fronts = [im.predict(method="decode_step") for _ in range(6)]
    assert all(f.shape == (2,) for f in fronts)
    for b, p in enumerate(prompts):
        got = [int(first[b])] + [int(f[b]) for f in fronts]
        assert got == _greedy_oracle(im.model, im.variables, p, 7)
    # the engine-backed session ran the compiled fixed-shape programs
    assert im.engine().compile_counts() == {"prefill": 1, "tick": 1}
    # generate() sugar matches the greedy oracle on a fresh bundle
    im2 = load_inference_model(path)
    outs = im2.generate(prompts, max_new_tokens=4, block_size=BS)
    for p, got in zip(prompts, outs):
        assert got == _greedy_oracle(im2.model, im2.variables, p, 4)


def test_inference_unhashable_kwarg_warns_once_naming_it(
        tmp_path, model_and_vars, caplog):
    from paddle_tpu.inference import export, load_inference_model
    model, vs = model_and_vars
    path = os.path.join(str(tmp_path), "bundle")
    export(path, model, vs)
    im = load_inference_model(path)
    x = jnp.zeros((1, W), jnp.int32)
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.inference"):
        im.predict(x, segments=np.ones((1, W), np.int32))   # unhashable
        im.predict(x, segments=np.ones((1, W), np.int32))   # warned already
    warns = [r for r in caplog.records if "unhashable" in r.getMessage()]
    assert len(warns) == 1
    assert "segments" in warns[0].getMessage()
