"""Serving runtime tests (ISSUE 9): paged KV cache correctness, the
decode-shaped Pallas kernel vs its oracle, engine/scheduler behavior, and
the two acceptance contracts —

- **KV correctness**: prefill + N x decode_step logits BIT-EQUAL (f32,
  CPU) to the full-sequence forward, for ragged lengths crossing block
  boundaries; and block free/reuse reproduces identical tokens after
  eviction churn (stale pool contents must be fully masked).
- **The no-retrace invariant**: one compiled program per entry point
  across arbitrary admission/eviction churn.
"""

import logging
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models import TransformerLM
from paddle_tpu.serve import (BlockAllocator, ContinuousBatchingScheduler,
                              DecodeEngine, PagedKVCache)
from paddle_tpu.serve import kv_cache as kvc

V, W, DIM, LAYERS, HEADS, FFN = 64, 24, 32, 2, 4, 64
BS, MB = 4, 6                        # block_size x max_blocks = W


@pytest.fixture(scope="module")
def model_and_vars():
    model = TransformerLM(vocab=V, dim=DIM, num_layers=LAYERS,
                          num_heads=HEADS, ffn_hidden=FFN, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))
    return model, vs


def _greedy_oracle(model, vs, prompt, n_new):
    """Token-by-token greedy decode through the full training forward."""
    fwd = jax.jit(lambda v, i: model.apply(v, i))
    seq, out = list(prompt), []
    for _ in range(n_new):
        pad = np.zeros((1, W), np.int32)
        pad[0, :len(seq)] = seq
        logits = fwd(vs, jnp.asarray(pad))
        tok = int(np.argmax(np.asarray(logits[0, len(seq) - 1])))
        out.append(tok)
        seq.append(tok)
    return out


# ---------------------------------------------------------------------------
# kv_cache: allocator + pure gather/scatter
# ---------------------------------------------------------------------------

def test_block_allocator_invariants():
    a = BlockAllocator(6)                    # blocks 1..5 usable
    assert a.num_free == 5
    got = a.alloc(3)
    assert got == [1, 2, 3] and a.num_free == 2
    assert a.alloc(3) is None and a.num_free == 2   # refuse, no change
    a.free([2])
    assert a.alloc(3) == [4, 5, 2]           # FIFO reuse
    with pytest.raises(AssertionError):
        a.free([kvc.NULL_BLOCK])


def test_cache_capacity_and_free(nprng):
    c = PagedKVCache(num_layers=1, num_heads=2, head_dim=4, num_blocks=5,
                     block_size=BS, max_slots=2, max_blocks_per_seq=MB)
    assert c.context_width == MB * BS
    assert c.ensure_capacity(0, 9)           # 3 blocks
    assert c.free_blocks == 1
    assert not c.ensure_capacity(1, 9)       # needs 3, 1 free: refuse
    assert c.free_blocks == 1                # refusal changed nothing
    assert c.ensure_capacity(1, 3)           # 1 block fits
    c.free_slot(0)
    assert c.free_blocks == 3
    assert (c.tables[0] == kvc.NULL_BLOCK).all() and c.lengths[0] == 0


def test_gather_scatter_roundtrip(nprng):
    H, hd = 2, 4
    pages = jnp.zeros((8, BS, H, hd), jnp.float32)
    table = jnp.asarray([[3, 1, 5, 0, 0, 0]], jnp.int32)
    kv = jnp.asarray(nprng.randn(1, MB * BS, H, hd).astype(np.float32))
    length = jnp.asarray([9], jnp.int32)
    pages = kvc.scatter_prefill(pages, kv, table, length)
    got = kvc.gather_pages(pages, table)
    np.testing.assert_array_equal(np.asarray(got[0, :9]),
                                  np.asarray(kv[0, :9]))
    # rows >= length went to the null block, not the sequence's pages:
    # row 8 is block 5 offset 0, so block 5's tail stays untouched
    assert not np.any(np.asarray(pages[5][1:]))

    tok = jnp.asarray(nprng.randn(1, H, hd).astype(np.float32))
    pages = kvc.scatter_token(pages, tok, table, jnp.asarray([9]),
                              jnp.asarray([True]))
    got = kvc.gather_pages(pages, table)
    np.testing.assert_array_equal(np.asarray(got[0, 9]), np.asarray(tok[0]))
    # inactive slots scatter to the null block only
    before = np.asarray(pages)
    pages2 = kvc.scatter_token(pages, tok * 7, table, jnp.asarray([9]),
                               jnp.asarray([False]))
    after = np.asarray(pages2)
    np.testing.assert_array_equal(before[1:], after[1:])


# ---------------------------------------------------------------------------
# the decode-shaped Pallas kernel vs its oracle
# ---------------------------------------------------------------------------

def test_paged_decode_attention_matches_reference(nprng):
    from paddle_tpu.nn.pallas_attention import (paged_decode_attention,
                                                paged_reference_attention)
    S, H, D, N = 4, 2, 16, 32
    q = jnp.asarray(nprng.randn(S, H, D).astype(np.float32))
    pk = jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32))
    pv = jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32))
    tables = jnp.asarray(nprng.randint(0, N, (S, MB)), jnp.int32)
    # ragged: mid-block, inactive, full capacity, block-boundary
    lengths = jnp.asarray([5, 0, MB * BS, 12], jnp.int32)
    out = paged_decode_attention(q, pk, pv, tables, lengths)
    ref = paged_reference_attention(q, pk, pv, tables, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)
    assert not np.any(np.asarray(out[1]))    # inactive slot: zeros


def test_model_decode_step_paged_impl_matches_xla(model_and_vars, nprng):
    """The Pallas paged path and the bit-exact XLA gather path agree
    (allclose — different softmax algebra) on the same cache state."""
    model, vs = model_and_vars
    hd = DIM // HEADS
    cache = PagedKVCache(LAYERS, HEADS, hd, 16, BS, max_slots=2,
                         max_blocks_per_seq=MB)
    ids = nprng.randint(0, V, (2, W)).astype(np.int32)
    _, (ks, vsv) = jax.jit(
        lambda v, i: model.apply(v, i, method="prefill"))(
            vs, jnp.asarray(ids))
    for b in range(2):
        assert cache.ensure_capacity(b, 10)
    tbl = jnp.asarray(cache.tables)
    plen = jnp.asarray([9, 6], jnp.int32)
    scat = jax.vmap(kvc.scatter_prefill, in_axes=(0, 0, None, None))
    cache.k = scat(cache.k, ks, tbl, plen)
    cache.v = scat(cache.v, vsv, tbl, plen)
    tok = jnp.asarray([3, 7], jnp.int32)
    act = jnp.asarray([True, False])      # one inactive lane
    outs = {}
    for impl in ("xla", "paged"):
        logits, _ = model.apply(vs, tok, (cache.k, cache.v, tbl), plen,
                                act, attn_impl=impl, method="decode_step")
        outs[impl] = np.asarray(logits)
    # both impls agree on the active lane AND on the inactive lane's
    # zero-context convention (the whole [S] front, not just active rows)
    np.testing.assert_allclose(outs["paged"], outs["xla"],
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# acceptance: prefill + N x decode_step BIT-EQUAL to the full forward
# ---------------------------------------------------------------------------

def test_prefill_decode_bit_equal_full_forward(model_and_vars, nprng):
    """f32 CPU: for ragged lengths crossing block boundaries, every
    decoded position's logits are bitwise identical to the full-sequence
    forward at the fixed padded width — the serving path introduces ZERO
    numeric drift over the training forward."""
    model, vs = model_and_vars
    B = 3
    lens = [13, W, 7]                 # mid-block, full, block-boundary+3
    P = 3                             # prefill length (rest decoded)
    ids = nprng.randint(0, V, (B, W)).astype(np.int32)
    oracle = np.asarray(jax.jit(lambda v, i: model.apply(v, i))(
        vs, jnp.asarray(ids)))

    hd = DIM // HEADS
    cache = PagedKVCache(LAYERS, HEADS, hd, B * MB + 1, BS, max_slots=B,
                         max_blocks_per_seq=MB)
    logits_pre, (ks, vsv) = jax.jit(
        lambda v, i: model.apply(v, i, method="prefill"))(
            vs, jnp.asarray(ids))
    # prefill logits themselves are bit-equal to forward
    np.testing.assert_array_equal(np.asarray(logits_pre), oracle)

    for b in range(B):
        assert cache.ensure_capacity(b, lens[b])
    tbl = jnp.asarray(cache.tables)
    plen = jnp.full((B,), P, jnp.int32)
    scat = jax.vmap(kvc.scatter_prefill, in_axes=(0, 0, None, None))
    cache.k = scat(cache.k, ks, tbl, plen)
    cache.v = scat(cache.v, vsv, tbl, plen)

    decode = jax.jit(lambda v, t, kv, pos, a: model.apply(
        v, t, kv, pos, a, method="decode_step"))
    for t in range(P, max(lens)):
        active = jnp.asarray([t < lens[b] for b in range(B)])
        pos = jnp.full((B,), t, jnp.int32)
        logits, (cache.k, cache.v, _) = decode(
            vs, jnp.asarray(ids[:, t]), (cache.k, cache.v, tbl), pos,
            active)
        for b in range(B):
            if t < lens[b]:
                np.testing.assert_array_equal(
                    np.asarray(logits[b]), oracle[b, t],
                    err_msg=f"slot {b} position {t}")


def test_block_free_reuse_identical_after_churn(model_and_vars, nprng):
    """Evicting sequences and re-admitting onto RECYCLED blocks (stale
    pool contents) reproduces the exact same generation — proof the
    length mask fully owns the block-content boundary."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       num_blocks=2 * MB + 1)
    prompt = list(nprng.randint(0, V, 5))
    sched = ContinuousBatchingScheduler(eng)
    first = sched.submit(prompt, 6)
    sched.run()
    assert first.done

    # churn: fill and free the pool with other sequences several times
    for i in range(3):
        s2 = ContinuousBatchingScheduler(eng)
        for j in range(3):
            s2.submit(list(nprng.randint(0, V, 4 + i + j)), 5 + j)
        s2.run()
    assert eng.cache.free_blocks == 2 * MB   # all returned

    again = ContinuousBatchingScheduler(eng)
    rerun = again.submit(prompt, 6)
    again.run()
    assert rerun.tokens == first.tokens      # bit-identical generation
    # and the whole time, nothing ever retraced
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}


# ---------------------------------------------------------------------------
# engine + scheduler
# ---------------------------------------------------------------------------

def test_continuous_batching_completes_and_matches_oracle(model_and_vars,
                                                          nprng):
    from paddle_tpu.obs import InMemorySink, Telemetry
    model, vs = model_and_vars
    mem = InMemorySink()
    eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                       telemetry=Telemetry(sinks=[mem]))
    sched = ContinuousBatchingScheduler(eng)
    prompts = [list(nprng.randint(0, V, nprng.randint(2, 8)))
               for _ in range(8)]
    maxnew = [3, 9, 5, 12, 7, 4, 10, 6]
    reqs = [sched.submit(p, m) for p, m in zip(prompts, maxnew)]
    done = sched.run()
    assert len(done) == 8 and all(r.done for r in reqs)
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}
    # per-request telemetry: one record each, with the SLO fields
    recs = mem.by_kind("request")
    assert len(recs) == 8
    for r in recs:
        assert r["ttft_ms"] is not None and r["ttft_ms"] >= 0
        assert r["new_tokens"] >= 1
        if r["new_tokens"] >= 2:
            assert r["tpot_ms"] is not None and r["tpot_ms"] >= 0
    assert len(mem.by_kind("decode_tick")) == eng.ticks
    # generated tokens match the naive greedy full-forward oracle
    for req, p, m in list(zip(reqs, prompts, maxnew))[:3]:
        assert req.tokens == _greedy_oracle(model, vs, p, m)


def test_static_policy_gangs_and_is_slower(model_and_vars, nprng):
    """The gang baseline completes but burns idle-lane ticks on ragged
    lengths — the differential continuous batching exists to win."""
    model, vs = model_and_vars
    prompts = [list(nprng.randint(0, V, 4)) for _ in range(8)]
    maxnew = [2, 12, 2, 2, 12, 2, 2, 2]      # stragglers pin their gang
    ticks = {}
    for policy in ("continuous", "static"):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=BS)
        sched = ContinuousBatchingScheduler(eng, policy=policy)
        reqs = [sched.submit(p, m) for p, m in zip(prompts, maxnew)]
        sched.run()
        assert all(r.done for r in reqs)
        ticks[policy] = eng.ticks
    assert ticks["static"] > ticks["continuous"]


def test_pool_backpressure_defers_admission(model_and_vars, nprng):
    """A pool sized for ~2 concurrent sequences serves 4 requests by
    deferring admissions until eviction frees blocks."""
    model, vs = model_and_vars
    # 2 sequences x 3 blocks each fit; the third admission must wait
    eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                       num_blocks=2 * 3 + 1)
    sched = ContinuousBatchingScheduler(eng)
    reqs = [sched.submit(list(nprng.randint(0, V, 5)), 6)
            for _ in range(4)]
    done = sched.run()
    assert len(done) == 4 and all(r.done for r in reqs)
    assert eng.cache.free_blocks == 6
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}


class _FakeClock:
    """Deterministic scheduler clock: the test advances it between
    ticks, so deadline expiry is exact, not wall-time-flaky."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_deadline_evicts_running_slot_and_frees_blocks(model_and_vars,
                                                       nprng):
    """ISSUE 10: a slot that exceeds its deadline_s is evicted between
    ticks with finish_reason="timeout" and its blocks freed — a stuck/
    long request can no longer hold a slot + reservation forever."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    model, vs = model_and_vars
    mem = InMemorySink()
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       telemetry=Telemetry(sinks=[mem]))
    clock = _FakeClock()
    sched = ContinuousBatchingScheduler(eng, clock=clock)
    free0 = eng.cache.free_blocks
    stuck = sched.submit(list(nprng.randint(0, V, 4)), 18, deadline_s=2.5)
    quick = sched.submit(list(nprng.randint(0, V, 4)), 3)
    while sched.step():
        clock.t += 1.0                        # one "second" per tick
    assert quick.done and quick.finish_reason == "length"
    assert stuck.done and stuck.finish_reason == "timeout"
    # evicted mid-decode: partial tokens, well short of max_new
    assert 1 <= len(stuck.tokens) < 18
    # the whole reservation came back to the pool
    assert eng.cache.free_blocks == free0
    assert not eng.active.any()
    # surfaced in the request telemetry records
    recs = {r["rid"]: r for r in mem.by_kind("request")}
    assert recs[stuck.rid]["finish_reason"] == "timeout"
    assert recs[stuck.rid]["deadline_s"] == 2.5
    assert recs[quick.rid]["finish_reason"] == "length"
    assert recs[quick.rid]["deadline_s"] is None


def test_deadline_drops_expired_queued_request(model_and_vars, nprng):
    """A request whose deadline expires while still QUEUED (pool/slot
    backpressure) is dropped before ever taking a slot."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=1, block_size=BS)
    clock = _FakeClock()
    sched = ContinuousBatchingScheduler(eng, clock=clock)
    free0 = eng.cache.free_blocks
    long_req = sched.submit(list(nprng.randint(0, V, 4)), 10)
    starved = sched.submit(list(nprng.randint(0, V, 4)), 4, deadline_s=3.0)
    while sched.step():
        clock.t += 1.0
    assert long_req.finish_reason == "length"
    assert starved.finish_reason == "timeout"
    assert starved.slot is None and starved.tokens == []
    # the timed-out request never took a slot or any blocks
    assert eng.cache.free_blocks == free0


def test_deadline_evictions_emit_records_and_return_blocks(model_and_vars,
                                                           nprng):
    """ISSUE 11 satellite: BOTH deadline-eviction paths are visible in
    telemetry — the queued drop emits a kind="evict" record (previously
    only slot evictions were distinguishable), and the running slot's
    exact block ids land back on the BlockAllocator free list (leak
    regression)."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    model, vs = model_and_vars
    mem = InMemorySink()
    eng = DecodeEngine(model, vs, max_slots=1, block_size=BS,
                       telemetry=Telemetry(sinks=[mem]))
    clock = _FakeClock()
    sched = ContinuousBatchingScheduler(eng, clock=clock)
    running = sched.submit(list(nprng.randint(0, V, 4)), 18,
                           deadline_s=2.5)
    starved = sched.submit(list(nprng.randint(0, V, 4)), 4,
                           deadline_s=2.0)   # expires before a slot frees
    sched.step()                      # admit `running`; blocks reserved
    owned = list(eng.cache._owned[running.slot])
    assert owned, "admission reserved no blocks"
    while sched.step():
        clock.t += 1.0
    assert running.finish_reason == "timeout"
    assert starved.finish_reason == "timeout" and starved.slot is None
    # the evicted slot's block ids are reclaimable — ON the free list or
    # parked in the retained LRU (ISSUE 14: a registered prefix block
    # outlives its owner there), never leaked in the refcount table
    reclaimable = (set(eng.cache.allocator._free)
                   | set(eng.cache.allocator._retained))
    assert set(owned) <= reclaimable
    assert eng.cache.free_blocks == eng.cache.num_blocks - 1
    evicts = {r["rid"]: r for r in mem.by_kind("evict")}
    assert evicts[running.rid]["where"] == "running"
    assert evicts[running.rid]["blocks_freed"] == len(owned)
    assert evicts[starved.rid]["where"] == "queued"
    assert evicts[starved.rid]["blocks_freed"] == 0


def test_scheduler_surfaces_structured_backpressure(model_and_vars,
                                                    nprng):
    """ISSUE 11 satellite: when admission stalls on the pool, the
    scheduler records WHY (blocks vs slots) so a router doesn't guess."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                       num_blocks=2 * 3 + 1)
    sched = ContinuousBatchingScheduler(eng)
    for _ in range(4):
        sched.submit(list(nprng.randint(0, V, 5)), 6)
    sched.step()
    assert sched.last_backpressure == "blocks"    # pool, not slots
    sched.run()
    assert sched.last_backpressure is None        # cleared when flowing
    # the static gang-wait path clears it too (no stale reason while
    # the gang runs)
    eng2 = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    s2 = ContinuousBatchingScheduler(eng2, policy="static")
    for _ in range(2):
        s2.submit(list(nprng.randint(0, V, 5)), 4)
    s2.step()
    s2.last_backpressure = "blocks"               # simulate a stale read
    s2.step()                                     # gang still running
    assert s2.last_backpressure is None


def test_deadline_none_is_unchanged_and_validation(model_and_vars, nprng):
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    sched = ContinuousBatchingScheduler(eng)
    with pytest.raises(ValueError, match="deadline_s"):
        sched.submit([1, 2], 2, deadline_s=-1.0)
    req = sched.submit(list(nprng.randint(0, V, 3)), 4)
    sched.run()
    assert req.finish_reason == "length" and len(req.tokens) == 4


def test_decode_past_reservation_raises(model_and_vars):
    """Out-decoding the admission reservation must fail loud, not scatter
    new-token KV into the null block (silent wrong logits)."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    eng.admit(0, [1, 2, 3])                  # reserves 1 block (3 tokens)
    eng.decode_tick()                        # position 3 fills block 0
    with pytest.raises(RuntimeError, match="past its reservation"):
        eng.decode_tick()                    # position 4 needs block 2


def test_prompt_capacity_validation(model_and_vars):
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    sched = ContinuousBatchingScheduler(eng)
    with pytest.raises(ValueError, match="exceeds slot capacity"):
        sched.submit(list(range(W)), 2)      # W + 2 > capacity W
    with pytest.raises(ValueError, match="max_new_tokens"):
        sched.submit([1, 2], 0)
    with pytest.raises(ValueError, match="attention"):
        DecodeEngine(model, vs, attention="nope")


# ---------------------------------------------------------------------------
# ISSUE 12: copy-on-write prefix sharing
# ---------------------------------------------------------------------------

def test_allocator_refcounts():
    a = BlockAllocator(6)
    got = a.alloc(2)
    assert got == [1, 2] and a.total_allocs == 2
    a.incref(1)                              # a second owner
    assert a.ref_count(1) == 2
    assert a.decref(1) is False              # co-owner holds on
    assert a.num_free == 3                   # nothing freed yet
    assert a.decref(1) is True               # last owner: freed
    assert a.num_free == 4 and a.ref_count(1) == 0
    with pytest.raises(AssertionError, match="double free"):
        a.decref(1)
    with pytest.raises(AssertionError):
        a.incref(5)                          # never allocated


def test_prefix_cache_chain_and_partial():
    from paddle_tpu.serve import PrefixCache
    pc = PrefixCache(block_size=4)
    prompt = list(range(10))                 # 2 full blocks + tail [8, 9]
    pc.register(prompt, [7, 8, 9])
    # full-chain walk + exact-tail partial hit
    m = pc.match(prompt)
    assert m.blocks == [7, 8, 9] and m.length == 10 and m.partial
    # a divergent tail keeps only the full-block chain
    m = pc.match(list(range(8)) + [99, 98, 97])
    assert m.blocks == [7, 8] and m.length == 8 and not m.partial
    # diverging INSIDE a block shares nothing of that block
    m = pc.match([0, 1, 2, 3, 99, 5, 6, 7])
    assert m.blocks == [7] and m.length == 4
    m = pc.match([99, 1, 2, 3])
    assert m.blocks == [] and m.length == 0
    # cumulative hashing: a matching second block under a different
    # first block is NOT a hit (the chain key encodes the whole prefix)
    m = pc.match([9, 9, 9, 9] + list(range(4, 8)))
    assert m.blocks == []
    # invalidation drops every entry for the freed block
    pc.invalidate_block(8)
    assert pc.match(prompt).blocks == [7]


def test_shared_prefix_fewer_allocs_and_leak_free(model_and_vars, nprng):
    """Concurrent requests sharing a prompt prefix map the SAME physical
    full blocks (fewer fresh allocations), generate bit-identical
    tokens, and every shared block returns to the free list exactly once
    after all sharers evict — the ISSUE 12 leak regression."""
    model, vs = model_and_vars
    pre = list(nprng.randint(0, V, 2 * BS))          # 2 full blocks
    prompts = [pre + list(nprng.randint(0, V, 3)) for _ in range(4)]

    def run(share):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                           share_prefix=share)
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(list(p), 5) for p in prompts]
        sched.run()
        return eng, [r.tokens for r in reqs], reqs

    eng_on, toks_on, reqs_on = run(True)
    eng_off, toks_off, _ = run(False)
    assert toks_on == toks_off               # sharing never changes tokens
    assert (eng_on.cache.allocator.total_allocs
            < eng_off.cache.allocator.total_allocs)
    assert eng_on.cache.prefix_hit_blocks >= 2   # followers adopted
    # zero leaks: every block exactly once across the free list and the
    # retained LRU (ISSUE 14: registered blocks outlive their owners
    # there — reclaimable, not leaked)
    pool = (list(eng_on.cache.allocator._free)
            + list(eng_on.cache.allocator._retained))
    assert len(pool) == len(set(pool)) == eng_on.cache.num_blocks - 1
    assert eng_on.compile_counts() == {"prefill": 1, "tick": 1}
    # request records carry the sharing attribution
    follower = [r for r in reqs_on if (r.prefix_hit_blocks or 0) > 0]
    assert follower and all(r.blocks_reserved for r in reqs_on)


def test_cow_fork_on_duplicate_prompts(model_and_vars, nprng):
    """An exact-duplicate prompt shares EVERY block including the
    partial boundary; the first divergent decode write forks exactly
    that block (copy-on-write), generations stay bit-identical, and the
    fork leaks nothing after full churn."""
    model, vs = model_and_vars
    prompt = list(nprng.randint(0, V, 6))    # partial boundary (6 % 4)
    eng = DecodeEngine(model, vs, max_slots=4, block_size=BS)
    sched = ContinuousBatchingScheduler(eng)
    r1 = sched.submit(list(prompt), 5)
    r2 = sched.submit(list(prompt), 5)
    sched.run()
    assert r1.tokens == r2.tokens
    assert eng.cache.cow_forks >= 1
    assert (r2.cow_forks or 0) + (r1.cow_forks or 0) >= 1
    # solo oracle: the same prompt on a fresh engine, no sharing at all
    eng2 = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                        share_prefix=False)
    s2 = ContinuousBatchingScheduler(eng2)
    solo = s2.submit(list(prompt), 5)
    s2.run()
    assert solo.tokens == r1.tokens
    pool = (list(eng.cache.allocator._free)
            + list(eng.cache.allocator._retained))
    assert len(pool) == len(set(pool)) == eng.cache.num_blocks - 1


def test_sharing_eviction_churn_bit_identity(model_and_vars, nprng):
    """Sharing under admission/eviction churn (the PR-9 churn test with
    share_prefix on): recycled blocks + invalidated cache entries
    reproduce the exact same generation, and nothing ever retraces."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       num_blocks=2 * MB + 3)
    pre = list(nprng.randint(0, V, BS))
    prompt = pre + list(nprng.randint(0, V, 2))
    sched = ContinuousBatchingScheduler(eng)
    first = sched.submit(list(prompt), 6)
    sched.run()
    # churn: session-style prompts fill, share, and free the pool
    for i in range(3):
        s2 = ContinuousBatchingScheduler(eng)
        for j in range(3):
            s2.submit(pre + list(nprng.randint(0, V, 1 + i + j)), 4 + j)
        s2.run()
    assert eng.cache.free_blocks == eng.cache.num_blocks - 1
    again = ContinuousBatchingScheduler(eng)
    rerun = again.submit(list(prompt), 6)
    again.run()
    assert rerun.tokens == first.tokens
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}


# ---------------------------------------------------------------------------
# ISSUE 12: lossless speculative decoding
# ---------------------------------------------------------------------------

def test_speculative_bit_identical_fewer_ticks(model_and_vars, nprng):
    """The acceptance contract: speculative greedy decode produces
    BIT-IDENTICAL tokens to the non-speculative engine on the ragged
    request set, with strictly fewer decode ticks, and the drafted
    width never retraces the pinned programs."""
    model, vs = model_and_vars
    prompts = [list(nprng.randint(0, V, nprng.randint(2, 8)))
               for _ in range(8)]
    maxnew = [3, 9, 5, 12, 7, 4, 10, 6]

    def run(k):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                           speculative=k)
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(list(p), m)
                for p, m in zip(prompts, maxnew)]
        sched.run()
        return eng, [r.tokens for r in reqs], reqs

    eng_b, toks_b, _ = run(0)
    eng_s, toks_s, reqs_s = run(3)
    assert toks_s == toks_b
    assert eng_s.ticks < eng_b.ticks
    assert eng_s.compile_counts() == {"prefill": 1, "tick": 1}
    assert eng_s.draft_proposed > 0
    # the per-request accept-rate attribution rides the records
    assert any(r.draft_accepted for r in reqs_s)
    # and the oracle: matches token-by-token greedy over the training
    # forward (transitively via toks_b, but pin one directly)
    assert toks_s[0] == _greedy_oracle(model, vs, prompts[0], maxnew[0])


def test_speculative_eos_and_deadline_semantics(model_and_vars, nprng):
    """A draft window crossing an EOS stops exactly where the
    sequential engine would (accepted tokens feed the finish rules one
    at a time), and speculation composes with deadline eviction."""
    model, vs = model_and_vars
    prompt = list(nprng.randint(0, V, 5))
    oracle = _greedy_oracle(model, vs, prompt, 12)
    eos = oracle[4]                          # stop at its FIRST occurrence
    expect = oracle[:oracle.index(eos) + 1]
    assert len(expect) < 12                  # genuinely mid-stream
    for k in (0, 3):
        eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                           speculative=k)
        sched = ContinuousBatchingScheduler(eng)
        req = sched.submit(list(prompt), 12, eos_id=eos)
        sched.run()
        assert req.finish_reason == "eos"
        assert req.tokens == expect, f"speculative={k}"


def test_speculative_capacity_clamp(model_and_vars, nprng):
    """A slot near its block reservation clamps the draft width instead
    of scattering past owned blocks — the guard that kept the plain
    tick honest keeps the fat tick honest too."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       speculative=4)
    sched = ContinuousBatchingScheduler(eng)
    req = sched.submit(list(nprng.randint(0, V, 3)), 4)
    sched.run()                              # reservation = 3 + 4 - 1
    assert req.finish_reason == "length" and len(req.tokens) == 4
    assert eng.cache.free_blocks == eng.cache.num_blocks - 1


def test_speculative_composes_with_sampling_rejection_rule(model_and_vars,
                                                           nprng):
    """ISSUE 14: the speculation×sampling guard is LIFTED — stochastic
    verification uses the [S3] rejection-sampling rule (accept draft d
    with prob p(d), resample rejections from the residual), which is
    (a) seeded-deterministic: a fixed seed replays the identical token
    stream, (b) distribution-preserving by construction — pinned here
    by the temperature→0 limit, where the rule degenerates to greedy
    acceptance and must match the greedy speculative engine EXACTLY."""
    from paddle_tpu.serve import SamplingConfig
    model, vs = model_and_vars
    prompts = [list(nprng.randint(0, V, 5)) for _ in range(3)]

    def run_sampled(seed, temp=1.0):
        eng = DecodeEngine(model, vs, max_slots=3, block_size=BS,
                           speculative=3,
                           sampling=SamplingConfig(temperature=temp,
                                                   seed=seed))
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(list(p), 8) for p in prompts]
        sched.run()
        assert eng.compile_counts() == {"prefill": 1, "tick": 1}
        return [r.tokens for r in reqs], eng

    a, eng_a = run_sampled(7)
    b, _ = run_sampled(7)
    c, _ = run_sampled(8)
    assert a == b                       # seeded-deterministic replay
    assert a != c                       # a different seed diverges
    assert all(len(t) == 8 for t in a)  # every request completed
    # temperature -> 0: p collapses onto the argmax, the accept coin
    # always lands under p(draft)==1 for agreeing drafts, and the
    # stream must equal the greedy speculative engine's token for token
    tiny, _ = run_sampled(7, temp=1e-4)
    eng_g = DecodeEngine(model, vs, max_slots=3, block_size=BS,
                         speculative=3)
    sg = ContinuousBatchingScheduler(eng_g)
    greedy = [sg.submit(list(p), 8) for p in prompts]
    sg.run()
    assert tiny == [r.tokens for r in greedy]


# ---------------------------------------------------------------------------
# ISSUE 12: chunked prefill
# ---------------------------------------------------------------------------

def test_chunked_prefill_bit_equal_and_interleaves(model_and_vars, nprng):
    """Chunked prefill produces the same first token and generation as
    the monolithic prefill (bit-equal span rows), and a long admission
    interleaves with a running slot's decode ticks instead of stalling
    its token stream."""
    model, vs = model_and_vars
    short_prompt = list(nprng.randint(0, V, 3))
    long_prompt = list(nprng.randint(0, V, 18))

    def run(chunk):
        eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                           prefill_chunk=chunk)
        sched = ContinuousBatchingScheduler(eng)
        short = sched.submit(list(short_prompt), 18)
        for _ in range(2):
            sched.step()
        before = len(short.tokens)
        long_req = sched.submit(list(long_prompt), 3)
        while long_req.first_token_ts is None and sched.step():
            pass
        interleaved = len(short.tokens) - before
        sched.run()
        return eng, short.tokens, long_req, interleaved

    eng_c, short_c, long_c, il_c = run(4)
    eng_f, short_f, long_f, il_f = run(None)
    assert short_c == short_f and long_c.tokens == long_f.tokens
    assert il_c > il_f                       # decode kept flowing
    assert eng_c.prefill_chunks > eng_f.prefill_chunks
    assert (long_c.prefill_chunks or 0) >= 5     # ceil(18/4)
    assert eng_c.compile_counts() == {"prefill": 1, "tick": 1}
    assert eng_f.compile_counts() == {"prefill": 1, "tick": 1}


def test_chunked_prefill_composes_with_sharing(model_and_vars, nprng):
    """Chunked prefill skips fully-shared chunks (the prefix-cache
    compute win) and still reproduces identical generations — including
    the exact-duplicate case that re-attends only the final position
    with writes masked."""
    model, vs = model_and_vars
    pre = list(nprng.randint(0, V, 2 * BS))
    donor_prompt = pre + list(nprng.randint(0, V, 3))
    dup = pre + [7, 7]

    def run(chunk, share):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                           prefill_chunk=chunk, share_prefix=share)
        sched = ContinuousBatchingScheduler(eng)
        # the donor must be RESIDENT (registered) before the sharers
        # admit — sharing is between concurrently-live sequences
        donor = sched.submit(list(donor_prompt), 12)
        for _ in range(4):
            sched.step()
        sharers = [sched.submit(list(dup), 4) for _ in range(2)]
        sched.run()
        return eng, [r.tokens for r in [donor] + sharers], \
            [donor] + sharers

    eng_a, toks_a, reqs_a = run(4, True)
    _, toks_b, _ = run(4, False)
    _, toks_c, _ = run(None, False)
    assert toks_a == toks_b == toks_c
    # the sharers' chunk counts shrink: adopted blocks skip their chunks
    by_chunks = [r.prefill_chunks for r in reqs_a]
    assert max(by_chunks[1], by_chunks[2]) < by_chunks[0]
    assert eng_a.cache.prefix_hit_blocks >= 2
    # the second duplicate exact-matches the first: one COW fork each
    # at the first divergent decode write
    assert eng_a.cache.cow_forks >= 1
    pool = (list(eng_a.cache.allocator._free)
            + list(eng_a.cache.allocator._retained))
    assert len(pool) == len(set(pool)) == eng_a.cache.num_blocks - 1


def test_decode_span_logits_bit_equal_full_forward(model_and_vars, nprng):
    """The ISSUE 12 acceptance invariant at LOGITS level: the span
    program (chunked prefill + speculative verify's shared core)
    produces rows bitwise identical (f32 CPU) to the full-sequence
    training forward — prefill a stub, then cover the rest of the
    sequence in ragged multi-token spans."""
    model, vs = model_and_vars
    B, P = 2, 3
    lens = [W, 14]                       # full capacity + mid-block
    ids = nprng.randint(0, V, (B, W)).astype(np.int32)
    oracle = np.asarray(jax.jit(lambda v, i: model.apply(v, i))(
        vs, jnp.asarray(ids)))
    hd = DIM // HEADS
    cache = PagedKVCache(LAYERS, HEADS, hd, B * MB + 1, BS, max_slots=B,
                         max_blocks_per_seq=MB)
    _, (ks, vsv) = jax.jit(
        lambda v, i: model.apply(v, i, method="prefill"))(
            vs, jnp.asarray(ids))
    for b in range(B):
        assert cache.ensure_capacity(b, lens[b])
    tbl = jnp.asarray(cache.tables)
    plen = jnp.full((B,), P, jnp.int32)
    scat = jax.vmap(kvc.scatter_prefill, in_axes=(0, 0, None, None))
    cache.k = scat(cache.k, ks, tbl, plen)
    cache.v = scat(cache.v, vsv, tbl, plen)
    span = jax.jit(lambda v, t, kv, s, n, a: model.apply(
        v, t, kv, s, n, a, method="decode_span"))
    Q = 5
    t = P
    while t < max(lens):
        n = jnp.asarray([max(0, min(Q, lens[b] - t)) for b in range(B)],
                        jnp.int32)
        active = n > 0
        chunk = np.zeros((B, Q), np.int32)
        for b in range(B):
            take = int(n[b])
            chunk[b, :take] = ids[b, t:t + take]
        logits, (cache.k, cache.v, _) = span(
            vs, jnp.asarray(chunk), (cache.k, cache.v, tbl),
            jnp.full((B,), t, jnp.int32), n, active)
        for b in range(B):
            for j in range(int(n[b])):
                np.testing.assert_array_equal(
                    np.asarray(logits[b, j]), oracle[b, t + j],
                    err_msg=f"slot {b} position {t + j}")
        t += Q


# ---------------------------------------------------------------------------
# ISSUE 12: stochastic sampling
# ---------------------------------------------------------------------------

def test_sampling_seeded_deterministic(model_and_vars, nprng):
    """Temperature/top-k/top-p sampling with per-slot keys: the same
    seed replays the exact token stream, a different seed diverges, and
    greedy (sampling=None) stays the bit-pinned default."""
    from paddle_tpu.serve import SamplingConfig
    model, vs = model_and_vars
    prompts = [list(nprng.randint(0, V, 4)) for _ in range(3)]

    def run(cfg):
        eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                           sampling=cfg)
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(list(p), 8) for p in prompts]
        sched.run()
        return [r.tokens for r in reqs], eng

    cfg = SamplingConfig(temperature=1.2, top_k=16, top_p=0.9, seed=3)
    a, eng_a = run(cfg)
    b, _ = run(cfg)
    c, _ = run(SamplingConfig(temperature=1.2, top_k=16, top_p=0.9,
                              seed=4))
    assert a == b                            # seeded-deterministic
    assert a != c                            # the seed is load-bearing
    assert eng_a.compile_counts() == {"prefill": 1, "tick": 1}
    greedy, _ = run(None)
    assert greedy[0] == _greedy_oracle(model, vs, prompts[0], 8)


def test_sampling_validation(model_and_vars):
    from paddle_tpu.serve import SamplingConfig
    model, vs = model_and_vars
    for bad in (SamplingConfig(temperature=0.0),
                SamplingConfig(top_k=0),
                SamplingConfig(top_k=V + 1),
                SamplingConfig(top_p=0.0),
                SamplingConfig(top_p=1.5)):
        with pytest.raises(ValueError):
            DecodeEngine(model, vs, max_slots=2, block_size=BS,
                         sampling=bad)


def test_sampling_top_k_one_is_greedy(model_and_vars, nprng):
    """top_k=1 collapses the categorical to argmax whatever the seed —
    a cheap structural check on the filter chain."""
    from paddle_tpu.serve import SamplingConfig
    model, vs = model_and_vars
    prompt = list(nprng.randint(0, V, 4))
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       sampling=SamplingConfig(top_k=1, seed=11))
    sched = ContinuousBatchingScheduler(eng)
    req = sched.submit(list(prompt), 6)
    sched.run()
    assert req.tokens == _greedy_oracle(model, vs, prompt, 6)


# ---------------------------------------------------------------------------
# ISSUE 12: telemetry fields
# ---------------------------------------------------------------------------

def test_tick_and_request_records_carry_throughput_fields(model_and_vars,
                                                          nprng):
    """Per-tick records carry prefix_hit_blocks / cow_forks /
    draft_accept_rate / prefill_chunks; request records carry the
    per-request attribution; summarize_requests aggregates accept rate
    and the block-sharing ratio (ISSUE 12 telemetry satellite)."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    from paddle_tpu.obs.percentiles import summarize_requests
    model, vs = model_and_vars
    mem = InMemorySink()
    pre = list(nprng.randint(0, V, BS))
    eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                       speculative=2, prefill_chunk=4,
                       telemetry=Telemetry(sinks=[mem]))
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(pre + list(nprng.randint(0, V, 2)), 8)
    for _ in range(3):
        sched.step()                 # the donor registers its prefix
    for i in range(3):
        sched.submit(pre + list(nprng.randint(0, V, 3 + i)), 6)
    sched.run()
    ticks = mem.by_kind("decode_tick")
    assert ticks
    for r in ticks:
        for key in ("prefix_hit_blocks", "cow_forks",
                    "draft_accept_rate", "prefill_chunks", "tokens"):
            assert key in r, key
    # counter fields are PER-TICK DELTAS: summing records == the
    # engine's cumulative truth (one aggregation rule per record)
    assert sum(r["prefix_hit_blocks"] for r in ticks) \
        == eng.cache.prefix_hit_blocks >= 1
    assert sum(r["prefill_chunks"] for r in ticks) <= eng.prefill_chunks
    reqs = mem.by_kind("request")
    assert len(reqs) == 4
    for r in reqs:
        for key in ("prefix_hit_blocks", "blocks_reserved", "cow_forks",
                    "prefill_chunks", "draft_accept_rate"):
            assert key in r, key
    summary = summarize_requests(reqs)
    assert summary["prefix_hit_blocks"] >= 1
    assert summary["block_sharing_ratio"] is not None
    assert summary["prefill_chunks"] >= 4
    assert summary["draft_accept_rate"] is None \
        or 0 <= summary["draft_accept_rate"] <= 1


# ---------------------------------------------------------------------------
# inference.py routing satellites
# ---------------------------------------------------------------------------

def test_inference_predict_routes_serving_methods(tmp_path, model_and_vars,
                                                  nprng):
    from paddle_tpu.inference import export, load_inference_model
    model, vs = model_and_vars
    path = os.path.join(str(tmp_path), "bundle")
    export(path, model, vs)
    im = load_inference_model(path)
    prompts = [[1, 2, 3], [5, 6, 7, 8]]
    first = im.predict(prompts, method="prefill", max_slots=2,
                       block_size=BS)
    assert first.shape == (2,)
    # decode well past the prompts' first block: the session reserves
    # full slot capacity at prefill, so crossing block boundaries keeps
    # matching the greedy full-forward oracle (regression: an
    # under-reserved session silently scattered KV to the null block)
    fronts = [im.predict(method="decode_step") for _ in range(6)]
    assert all(f.shape == (2,) for f in fronts)
    for b, p in enumerate(prompts):
        got = [int(first[b])] + [int(f[b]) for f in fronts]
        assert got == _greedy_oracle(im.model, im.variables, p, 7)
    # the engine-backed session ran the compiled fixed-shape programs
    assert im.engine().compile_counts() == {"prefill": 1, "tick": 1}
    # generate() sugar matches the greedy oracle on a fresh bundle
    im2 = load_inference_model(path)
    outs = im2.generate(prompts, max_new_tokens=4, block_size=BS)
    for p, got in zip(prompts, outs):
        assert got == _greedy_oracle(im2.model, im2.variables, p, 4)


def test_inference_unhashable_kwarg_warns_once_naming_it(
        tmp_path, model_and_vars, caplog):
    from paddle_tpu.inference import export, load_inference_model
    model, vs = model_and_vars
    path = os.path.join(str(tmp_path), "bundle")
    export(path, model, vs)
    im = load_inference_model(path)
    x = jnp.zeros((1, W), jnp.int32)
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.inference"):
        im.predict(x, segments=np.ones((1, W), np.int32))   # unhashable
        im.predict(x, segments=np.ones((1, W), np.int32))   # warned already
    warns = [r for r in caplog.records if "unhashable" in r.getMessage()]
    assert len(warns) == 1
    assert "segments" in warns[0].getMessage()


# ---------------------------------------------------------------------------
# ISSUE 14: int8 KV quantization
# ---------------------------------------------------------------------------

def test_quantize_rows_roundtrip_bound(nprng):
    """Symmetric per-row-per-head int8: reconstruction error is bounded
    by half a quantization step (amax/254) per element."""
    kv = jnp.asarray(nprng.randn(3, 5, 4, 16).astype(np.float32))
    q, s = kvc.quantize_rows(kv)
    assert q.dtype == jnp.int8 and s.shape == (3, 5, 4)
    deq = kvc.dequantize_rows(q, s)
    amax = np.max(np.abs(np.asarray(kv)), axis=-1, keepdims=True)
    err = np.abs(np.asarray(deq) - np.asarray(kv))
    assert np.all(err <= amax / 254.0 + 1e-7)


def test_quantized_pool_scatter_gather_dequantizes(nprng):
    """The (values, scales) tuple pool: scatter quantizes, gather
    returns dequantized f32 close to the original rows."""
    H, hd = 2, 8
    pages = (jnp.zeros((8, BS, H, hd), jnp.int8),
             jnp.zeros((8, BS, H), jnp.float32))
    table = jnp.asarray([[3, 1, 5, 0, 0, 0]], jnp.int32)
    kv = jnp.asarray(nprng.randn(1, MB * BS, H, hd).astype(np.float32))
    pages = kvc.scatter_prefill_pages(pages, kv, table,
                                      jnp.asarray([9], jnp.int32))
    got = kvc.gather_pages(pages, table)
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got[0, :9]),
                               np.asarray(kv[0, :9]), atol=0.03)


def test_quantized_engine_drift_bound_and_token_agreement(model_and_vars,
                                                          nprng):
    """The ISSUE 14 acceptance contract on the gate set: an int8 KV pool
    generates with >= 99% greedy token agreement vs the f32 pool, and
    the decode-step logits drift stays within a small absolute bound —
    quantization is a capacity lever, not a quality cliff."""
    model, vs = model_and_vars
    prompts = [list(nprng.randint(0, V, nprng.randint(2, 8)))
               for _ in range(8)]
    maxnew = [3, 9, 5, 12, 7, 4, 10, 6]

    def run(kv_dtype):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                           kv_dtype=kv_dtype)
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(list(p), m)
                for p, m in zip(prompts, maxnew)]
        sched.run()
        assert eng.compile_counts() == {"prefill": 1, "tick": 1}
        return [r.tokens for r in reqs], eng

    toks_f, eng_f = run(None)
    toks_q, eng_q = run("int8")
    agree = sum(a == b for x, y in zip(toks_f, toks_q)
                for a, b in zip(x, y))
    total = sum(len(x) for x in toks_f)
    assert agree / total >= 0.99
    # capacity accounting: int8 + one f32 scale per head vs 4 bytes/elem
    assert eng_q.cache.kv_bytes_per_token < eng_f.cache.kv_bytes_per_token
    assert eng_q.cache.quant_dtype == "int8"
    # logit drift on a live decode step, both caches warm with the same
    # prompt: small absolute bound at this model's logit scale
    ef = DecodeEngine(model, vs, max_slots=1, block_size=BS)
    eq = DecodeEngine(model, vs, max_slots=1, block_size=BS,
                      kv_dtype="int8")
    p0 = prompts[1]
    for e in (ef, eq):
        e.admit(0, list(p0), reserve_len=len(p0) + 4)

    def step_logits(e):
        tables, lengths = e.cache.device_tables()
        logits, _ = model.apply(
            e.variables, jnp.asarray(e.tokens),
            (e.cache.k, e.cache.v, tables), lengths,
            jnp.asarray(e.active), attn_impl="xla", method="decode_step")
        return np.asarray(logits[0])

    lf, lq = step_logits(ef), step_logits(eq)
    assert np.max(np.abs(lf - lq)) < 0.05 * max(1.0, np.ptp(lf))


def test_quantized_paged_kernel_matches_reference(nprng):
    """paged_decode_attention with an int8 (values, scales) pool matches
    the dequantizing oracle — dequant-in-kernel is numerically the same
    as dequant-then-attend."""
    from paddle_tpu.nn.pallas_attention import (paged_decode_attention,
                                                paged_reference_attention)
    S, H, D, N = 4, 2, 16, 32
    q = jnp.asarray(nprng.randn(S, H, D).astype(np.float32))
    raw_k = jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32))
    raw_v = jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32))
    pk = kvc.quantize_rows(raw_k)
    pv = kvc.quantize_rows(raw_v)
    tables = jnp.asarray(nprng.randint(0, N, (S, MB)), jnp.int32)
    lengths = jnp.asarray([5, 0, MB * BS, 12], jnp.int32)
    out = paged_decode_attention(q, pk, pv, tables, lengths)
    ref = paged_reference_attention(q, pk, pv, tables, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)
    assert not np.any(np.asarray(out[1]))


# ---------------------------------------------------------------------------
# ISSUE 14: multi-query paged span kernel
# ---------------------------------------------------------------------------

def test_paged_span_kernel_matches_oracle_and_q1_bit_exact(nprng):
    """The span kernel vs its oracle across ragged starts (mid-block,
    block-boundary, tail), span widths Q = 1+k for k in {0, 3}, partial
    spans (n < Q) and an inactive slot — and at Q=1 the kernel runs the
    EXACT op sequence of the q_len=1 decode kernel (bit-equal: the
    greedy-path contract)."""
    from paddle_tpu.nn.pallas_attention import (
        paged_decode_attention, paged_span_attention,
        paged_span_reference_attention)
    S, H, D, N = 4, 2, 16, 32
    pk = jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32))
    pv = jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32))
    tables = jnp.asarray(nprng.randint(0, N, (S, MB)), jnp.int32)
    for k in (0, 3):
        Q = 1 + k
        q = jnp.asarray(nprng.randn(S, Q, H, D).astype(np.float32))
        # mid-block, inactive WITH a stale start (must still be zeros),
        # block boundary, clamped tail
        start = jnp.asarray([3, 7, 8, MB * BS - Q], jnp.int32)
        n = jnp.asarray([Q, 0, max(1, Q - 1), Q], jnp.int32)
        out = paged_span_attention(q, pk, pv, tables, start, n)
        ref = paged_span_reference_attention(q, pk, pv, tables, start, n)
        for s in range(S):
            live = int(n[s])
            if live == 0:
                assert not np.any(np.asarray(out[s]))
            else:
                np.testing.assert_allclose(
                    np.asarray(out[s, :live]), np.asarray(ref[s, :live]),
                    rtol=2e-6, atol=2e-6)
        if Q == 1:
            lengths = jnp.where(n > 0, start + 1, 0)
            single = paged_decode_attention(q[:, 0], pk, pv, tables,
                                            lengths)
            np.testing.assert_array_equal(np.asarray(out[:, 0]),
                                          np.asarray(single))


def test_paged_span_kernel_quantized(nprng):
    """The span kernel's in-VMEM dequant path vs the dequantizing
    oracle (int8 pools)."""
    from paddle_tpu.nn.pallas_attention import (
        paged_span_attention, paged_span_reference_attention)
    S, Q, H, D, N = 3, 4, 2, 16, 32
    q = jnp.asarray(nprng.randn(S, Q, H, D).astype(np.float32))
    pk = kvc.quantize_rows(
        jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32)))
    pv = kvc.quantize_rows(
        jnp.asarray(nprng.randn(N, BS, H, D).astype(np.float32)))
    tables = jnp.asarray(nprng.randint(0, N, (S, MB)), jnp.int32)
    start = jnp.asarray([2, 0, 9], jnp.int32)
    n = jnp.asarray([Q, 0, Q], jnp.int32)
    out = paged_span_attention(q, pk, pv, tables, start, n)
    ref = paged_span_reference_attention(q, pk, pv, tables, start, n)
    for s in range(S):
        live = int(n[s])
        if live:
            np.testing.assert_allclose(
                np.asarray(out[s, :live]), np.asarray(ref[s, :live]),
                rtol=2e-6, atol=2e-6)


def test_model_decode_span_paged_impl_matches_xla(model_and_vars, nprng):
    """End to end through the model: the span tick on the paged kernel
    path produces tokens identical to the XLA gather path on this CPU
    gate set (the kernel is tolerance-accurate; argmax agreement over
    the gate set is the behavioral check)."""
    model, vs = model_and_vars
    prompts = [list(nprng.randint(0, V, nprng.randint(2, 8)))
               for _ in range(4)]

    def run(attention):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=BS,
                           speculative=3, attention=attention)
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(list(p), 8) for p in prompts]
        sched.run()
        assert eng.compile_counts() == {"prefill": 1, "tick": 1}
        return [r.tokens for r in reqs]

    assert run("paged") == run("xla")


# ---------------------------------------------------------------------------
# ISSUE 14: radix retention
# ---------------------------------------------------------------------------

def test_retention_sequential_prefix_hits(model_and_vars, nprng):
    """The RadixAttention win: a SECOND wave of same-prefix requests —
    no live sharer left — adopts retained blocks (fewer fresh allocs
    than a retention-off engine), generates identically, and the pool
    stays leak-free with retained counted as reclaimable."""
    model, vs = model_and_vars
    pre = list(nprng.randint(0, V, 2 * BS))
    tails = [list(nprng.randint(0, V, 3)) for _ in range(4)]

    def wave(eng, i):
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(pre + list(t), 4) for t in tails[2*i:2*i+2]]
        sched.run()
        return [r.tokens for r in reqs]

    eng_r = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    eng_n = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                         retain_prefix=False)
    toks_r = wave(eng_r, 0)
    assert eng_r.cache.retained_blocks > 0        # wave 1 parked blocks
    toks_n = wave(eng_n, 0)
    a_r, a_n = (eng_r.cache.allocator.total_allocs,
                eng_n.cache.allocator.total_allocs)
    toks_r2 = wave(eng_r, 1)
    toks_n2 = wave(eng_n, 1)
    assert toks_r == toks_n and toks_r2 == toks_n2   # identical output
    assert eng_r.cache.retained_hits >= 2        # wave 2 hit the LRU
    # wave 2 allocated FEWER fresh blocks than the retention-off engine
    assert (eng_r.cache.allocator.total_allocs - a_r
            < eng_n.cache.allocator.total_allocs - a_n)
    # leak-free: free + retained covers the whole pool exactly once
    pool = (list(eng_r.cache.allocator._free)
            + list(eng_r.cache.allocator._retained))
    assert len(pool) == len(set(pool)) == eng_r.cache.num_blocks - 1
    assert eng_r.cache.free_blocks == eng_r.cache.num_blocks - 1


def test_retention_reclaim_under_pressure_leak_free(model_and_vars,
                                                    nprng):
    """The retention leak regression (ISSUE 14): under pool pressure
    retained blocks are lazily reclaimed (oldest first, prefix-cache
    entries invalidated at that moment) — churn through MANY distinct
    prompts on a small pool, then verify every block is on the free
    list or retained LRU exactly once and reclaims actually fired."""
    model, vs = model_and_vars
    # pool sized for ~2 resident sequences: wave churn forces reclaim
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       num_blocks=2 * 3 + 1)
    for i in range(4):
        sched = ContinuousBatchingScheduler(eng)
        for j in range(3):
            sched.submit(list(nprng.randint(0, V, 4 + i + j)), 5)
        sched.run()
    assert eng.cache.allocator.retained_reclaims > 0
    pool = (list(eng.cache.allocator._free)
            + list(eng.cache.allocator._retained))
    assert len(pool) == len(set(pool)) == eng.cache.num_blocks - 1
    assert eng.cache.free_blocks == eng.cache.num_blocks - 1
    # the prefix cache holds no entry for any reclaimed (now-free) block
    for b in eng.cache.allocator._free:
        assert not eng.cache.prefix_cache.covers(b) or \
            b in eng.cache.allocator._retained
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}


def test_retention_cow_fork_interaction(model_and_vars, nprng):
    """Retention x CoW (ISSUE 14 satellite): re-admitting an exact
    prompt whose blocks sit in the retained LRU increfs them OUT of the
    LRU (retained hit, rc back to 1), the partial boundary block is
    handled by the standard promote-or-fork discipline, and generation
    is identical to the first run."""
    model, vs = model_and_vars
    prompt = list(nprng.randint(0, V, 6))        # partial boundary
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    s1 = ContinuousBatchingScheduler(eng)
    r1 = s1.submit(list(prompt), 5)
    s1.run()
    retained = list(eng.cache.allocator._retained)
    assert retained, "first run retained nothing"
    hits0 = eng.cache.retained_hits
    s2 = ContinuousBatchingScheduler(eng)
    r2 = s2.submit(list(prompt), 5)
    s2.run()
    assert r2.tokens == r1.tokens
    assert eng.cache.retained_hits > hits0
    # the adopted blocks left the LRU at adoption (incref-revive), and
    # after the second eviction they are retained or free again — once
    pool = (list(eng.cache.allocator._free)
            + list(eng.cache.allocator._retained))
    assert len(pool) == len(set(pool)) == eng.cache.num_blocks - 1


def test_admit_probe_counts_retained_as_reclaimable(model_and_vars,
                                                    nprng):
    """ISSUE 14 satellite: admit_probe threads the reclaimable count —
    a pool whose RAW free list is too small but whose retained LRU
    covers the need admits (no spurious "blocks" shed); the probe
    carries both numbers."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       num_blocks=2 * 3 + 1)
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(list(nprng.randint(0, V, 2 * BS)), 4)
    sched.run()                        # evicted -> full blocks retained
    assert eng.cache.retained_blocks > 0
    raw_free = eng.cache.allocator.num_free
    need_len = (raw_free + 1) * BS     # needs more than raw free
    assert eng.cache.blocks_needed(need_len) <= eng.cache.free_blocks
    probe = eng.admit_probe(need_len, include_slots=False)
    assert probe.ok and probe.reason is None
    assert probe.raw_free_blocks == raw_free
    assert probe.retained_blocks == eng.cache.retained_blocks
    assert probe.free_blocks == raw_free + probe.retained_blocks
    # and the pool genuinely serves it: admission reclaims lazily
    s2 = ContinuousBatchingScheduler(eng)
    req = s2.submit(list(nprng.randint(0, V, need_len - 2)), 2)
    s2.run()
    assert req.finish_reason == "length"


def test_decode_tick_records_carry_retention_and_quant_fields(
        model_and_vars, nprng):
    """ISSUE 14 telemetry: decode_tick records carry kv_bytes_per_token,
    retained_blocks, retained_hits (per-tick delta) and quant_dtype;
    summarize_requests aggregates them into retention-hit-rate and
    KV-bytes rows; obs.report renders them."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    from paddle_tpu.obs.percentiles import summarize_requests
    from paddle_tpu.obs.report import format_summary, summarize
    model, vs = model_and_vars
    mem = InMemorySink()
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       kv_dtype="int8", telemetry=Telemetry(sinks=[mem]))
    pre = list(nprng.randint(0, V, BS))
    for tail in ([1, 2], [3, 4]):      # sequential same-prefix sessions
        sched = ContinuousBatchingScheduler(eng)
        sched.submit(pre + tail, 3)
        sched.run()
    recs = mem.by_kind("decode_tick")
    assert recs
    for r in recs:
        assert r["kv_bytes_per_token"] == eng.cache.kv_bytes_per_token
        assert r["quant_dtype"] == "int8"
        assert "retained_blocks" in r and "retained_hits" in r
    assert sum(r["retained_hits"] for r in recs) >= 1
    summary = summarize_requests(mem.records)
    assert summary["retained_hits"] >= 1
    assert summary["kv_bytes_per_token"] == eng.cache.kv_bytes_per_token
    assert summary["quant_dtype"] == "int8"
    assert summary["retention_hit_rate"] is not None
    text = format_summary(summarize(mem.records))
    assert "retained prefix hits" in text
    assert "KV bytes/token" in text


# ---------------------------------------------------------------------------
# tensor-parallel sharded decode tick (ISSUE 15)
# ---------------------------------------------------------------------------
#
# The tp=2 engine runs the SAME two compiled programs over a 2-device
# mesh (conftest forces 8 virtual CPU devices): params placed by the
# megatron rule, KV pools head-sharded, out/ffn2 all-reduced. The
# contract is the one every serving PR pinned — token-identical (greedy,
# f32) to the single-device engine across admit/evict/CoW/speculative
# churn, with compile_counts() == {prefill: 1, tick: 1} and the host
# side fully shard-oblivious.


def _tp_mesh():
    from jax.sharding import Mesh
    assert len(jax.devices()) >= 2, "conftest forces 8 CPU devices"
    return Mesh(np.asarray(jax.devices()[:2]), ("model",))


def _churn_run(model, vs, mesh, waves=1, **kw):
    """One engine, `waves` sequential scheduler waves of 8 ragged
    requests over 4 slots (admissions + evictions churn within and
    across waves). Returns (per-wave token lists, engine)."""
    eng = DecodeEngine(model, vs, max_slots=4, block_size=BS, mesh=mesh,
                       **kw)
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(0, V, rng.randint(2, 8)))
               for _ in range(8)]
    maxnew = [2, 12, 2, 12, 2, 12, 2, 2]
    out = []
    for _ in range(waves):
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(p, m) for p, m in zip(prompts, maxnew)]
        sched.run()
        out.append([r.tokens for r in reqs])
    return out, eng


def test_tp_engine_token_identical_greedy_churn(model_and_vars):
    """The tentpole pin: tp=2 greedy tokens == single-device greedy
    tokens across two full admit/evict waves on one engine, with zero
    retraces after warmup (wave 2 reuses wave 1's two programs) and the
    per-shard KV accounting halved."""
    model, vs = model_and_vars
    base, eng_b = _churn_run(model, vs, None, waves=2)
    tp, eng_t = _churn_run(model, vs, _tp_mesh(), waves=2)
    assert tp == base
    assert eng_t.tp_degree == 2 and eng_b.tp_degree == 1
    assert eng_t.compile_counts() == {"prefill": 1, "tick": 1}
    assert eng_b.compile_counts() == {"prefill": 1, "tick": 1}
    # head split halves the per-shard bytes; block math is unchanged
    assert eng_t.cache.kv_bytes_per_token * 2 \
        == eng_b.cache.kv_bytes_per_token
    assert eng_t.cache.blocks_needed(13) == eng_b.cache.blocks_needed(13)
    # leak-free after both waves: every block back (free or retained)
    assert eng_t.cache.free_blocks == eng_t.cache.num_blocks - 1


def test_tp_engine_stochastic_speculative_identical(model_and_vars):
    """Seeded stochastic sampling x speculation under tp: the [S3]
    accept/resample walk replays the exact single-device token stream
    (same seeds, same coins — the tp mesh only changes WHERE the matmuls
    run, never the sampled distribution)."""
    from paddle_tpu.serve import SamplingConfig
    model, vs = model_and_vars
    cfg = SamplingConfig(temperature=0.8, top_k=16, seed=11)
    base, _ = _churn_run(model, vs, None, speculative=3, sampling=cfg)
    tp, eng = _churn_run(model, vs, _tp_mesh(), speculative=3,
                         sampling=cfg)
    assert tp == base
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}


def test_tp_engine_int8_pools_identical(model_and_vars):
    """Quantized pools under tp: int8 value pages AND f32 scale pages
    shard on the head axis; quantize-on-scatter/dequant-on-gather run
    per shard. Tokens match the single-device int8 engine exactly."""
    model, vs = model_and_vars
    base, eng_b = _churn_run(model, vs, None, kv_dtype="int8")
    tp, eng_t = _churn_run(model, vs, _tp_mesh(), kv_dtype="int8")
    assert tp == base
    assert eng_t.compile_counts() == {"prefill": 1, "tick": 1}
    # per-shard int8 accounting: half the heads' values+scales per token
    assert eng_t.cache.kv_bytes_per_token * 2 \
        == eng_b.cache.kv_bytes_per_token


def test_tp_cow_fork_and_retention_under_sharding(model_and_vars,
                                                  nprng):
    """Sharing composes with sharding: duplicate prompts adopt + COW-
    fork (the donated one-block device copy runs on the sharded pools),
    a second same-prefix wave revives retained blocks, and the pool
    stays leak-free — all through the ONE logical block table the host
    keeps (shard-obliviousness is the design's point)."""
    model, vs = model_and_vars
    pre = list(nprng.randint(0, V, 2 * BS))
    tails = [list(nprng.randint(0, V, 2)) for _ in range(4)]

    def run(mesh):
        eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                           mesh=mesh)
        toks = []
        # wave 1: a CONCURRENT exact-duplicate pair (both slots resident
        # at once) -> full-chain adoption + partial-boundary COW fork;
        # wave 2: fresh same-prefix tails with no live sharer ->
        # retained-LRU hits
        for wave in ([tails[0], tails[0]], tails[2:]):
            sched = ContinuousBatchingScheduler(eng)
            reqs = [sched.submit(pre + t, 4) for t in wave]
            sched.run()
            toks.append([r.tokens for r in reqs])
        return toks, eng

    base, eng_b = run(None)
    tp, eng_t = run(_tp_mesh())
    assert tp == base
    assert eng_t.cache.cow_forks >= 1           # forks actually fired
    assert eng_t.cache.retained_hits >= 1       # retention revived
    assert eng_t.cache.cow_forks == eng_b.cache.cow_forks
    assert eng_t.cache.retained_hits == eng_b.cache.retained_hits
    assert eng_t.cache.free_blocks == eng_t.cache.num_blocks - 1
    assert eng_t.compile_counts() == {"prefill": 1, "tick": 1}


def test_tp_paged_kernel_runs_per_shard(model_and_vars):
    """attention='paged' under a tp mesh: the Pallas q_len=1 and span
    kernels run PER SHARD over local heads via shard_map (the
    _tp_paged_kernel seam) and reproduce the xla path's greedy tokens."""
    model, vs = model_and_vars

    def run(attention, speculative=0):
        eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                           mesh=_tp_mesh(), attention=attention,
                           speculative=speculative)
        eng.admit(0, [1, 2, 3, 4, 5], reserve_len=eng.context_width)
        return [int(eng.decode_tick()[0]) for _ in range(4)], eng

    tx, _ = run("xla")
    tk, eng = run("paged")
    assert tk == tx
    assert eng.compile_counts() == {"prefill": 1, "tick": 1}
    # the span kernel (speculative tick) per shard
    sx, _ = run("xla", speculative=2)
    sk, _ = run("paged", speculative=2)
    assert sk == sx


def test_tp_kv_cache_accounting_and_validation():
    """PagedKVCache(tp_degree=): per-shard bytes divide by the head
    split, block math never changes, and a non-dividing head count
    fails loud (the kernel path needs whole head groups)."""
    mk = lambda tp: PagedKVCache(num_layers=2, num_heads=4, head_dim=8,
                                 num_blocks=9, block_size=BS,
                                 max_slots=2, max_blocks_per_seq=4,
                                 tp_degree=tp)
    c1, c2 = mk(1), mk(2)
    assert c2.kv_bytes_per_token * 2 == c1.kv_bytes_per_token
    assert c2.bytes_per_block * 2 == c1.bytes_per_block
    assert c2.blocks_needed(9) == c1.blocks_needed(9)
    with pytest.raises(ValueError, match="divide"):
        mk(3)
    with pytest.raises(ValueError, match="model"):
        # a mesh without the tp axis fails loud in the engine
        from jax.sharding import Mesh
        model = TransformerLM(vocab=V, dim=DIM, num_layers=1,
                              num_heads=HEADS, ffn_hidden=FFN, max_len=W)
        vs = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, W), jnp.int32))
        DecodeEngine(model, vs, mesh=Mesh(np.asarray(jax.devices()[:2]),
                                          ("data",)))


def test_tp_decode_tick_records_and_report(model_and_vars):
    """ISSUE 15 telemetry: decode_tick records carry tp_degree and the
    PER-SHARD kv_bytes_per_token; summarize_requests surfaces the mesh
    gauge; obs.report renders the tensor-parallel row."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    from paddle_tpu.obs.percentiles import summarize_requests
    from paddle_tpu.obs.report import format_summary, summarize
    model, vs = model_and_vars
    mem = InMemorySink()
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       mesh=_tp_mesh(), telemetry=Telemetry(sinks=[mem]))
    sched = ContinuousBatchingScheduler(eng)
    sched.submit([1, 2, 3], 4)
    sched.run()
    recs = mem.by_kind("decode_tick")
    assert recs
    for r in recs:
        assert r["tp_degree"] == 2
        assert r["kv_bytes_per_token"] == eng.cache.kv_bytes_per_token
    summary = summarize_requests(mem.records)
    assert summary["tp_degree"] == 2
    text = format_summary(summarize(mem.records))
    assert "tensor-parallel mesh" in text and "tp=2" in text


def test_tp_attribution_classifies_decode_collectives(model_and_vars):
    """ISSUE 15 satellite: the sharded tick's tp collectives (the
    out-proj/ffn all-reduces under decode/* scopes) classify into the
    serving comm table — region='decode', aggregated under
    report['decode']['comm'] — instead of falling through unlabeled."""
    from paddle_tpu.obs.attribution import format_report
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       mesh=_tp_mesh())
    rep = eng.attribution_report(emit=False)
    assert rep["n_devices"] == 2 and rep["tp_degree"] == 2
    comm = rep["decode"]["comm"]
    assert comm["ops"] >= 1 and comm["wire_bytes_total"] > 0
    assert comm["kinds"].get("all-reduce", 0) >= 1
    for row in comm["collectives"]:
        assert row["scope"].startswith("decode/")
    for c in rep["collectives"]:
        assert c["region"] == "decode"
    assert "decode tp comm" in format_report(rep)
    # the single-device tick keeps its collective-free report shape
    eng1 = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    rep1 = eng1.attribution_report(emit=False)
    assert "comm" not in (rep1["decode"] or {})


def test_proc_spec_ships_mesh_and_single_device_roundtrip(
        model_and_vars, tmp_path):
    """ISSUE 15 satellite: build_proc_spec(mesh_axes=) ships the axis
    layout (a Mesh can't cross the JSON wire); a spec WITHOUT it is
    byte-identical to the pre-tp schema (old/new replicas agree on the
    frame bytes), and replica_proc._build raises the mesh into a real
    tensor-parallel engine."""
    import json
    from paddle_tpu.serve import build_proc_spec
    from paddle_tpu.serve import replica_proc
    model, vs = model_and_vars
    plain = build_proc_spec(model, vs, str(tmp_path))
    assert "mesh" not in plain
    assert json.loads(json.dumps(plain)) == plain       # round-trips
    meshy = build_proc_spec(model, vs, str(tmp_path),
                            mesh_axes={"model": 2})
    assert meshy["mesh"] == {"model": 2}
    assert {k: v for k, v in meshy.items() if k != "mesh"} == plain
    eng, sched, buf, clock, startup, metrics = replica_proc._build(
        dict(meshy, engine={"max_slots": 2, "block_size": BS}))
    assert metrics is None              # absent spec key = no registry
    assert eng.tp_degree == 2
    assert eng.cache.kv_bytes_per_token * 2 == 512      # per-shard
    # ISSUE 16: startup breakdown exists even with warmup off — the
    # hello/heartbeat payloads always carry the build wall
    assert startup["build"] > 0 and startup["warmup"] == 0.0
    # warmup/cache fields stay ABSENT from an unconfigured spec (the
    # PR-15 schema-stability rule extends to the ISSUE-16 fields)
    for k in ("warmup", "compile_cache_dir", "autotune_cache_dir"):
        assert k not in plain
