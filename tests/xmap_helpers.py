"""Top-level picklable mappers for test_xmap: spawn workers unpickle these
by importing THIS module, which deliberately avoids jax so worker startup
stays cheap on the 1-core bench host."""

import time

import numpy as np


def square(x):
    return x * x


def slow_square(x):
    # jitter completion order so ordered/unordered behavior is observable
    time.sleep(0.05 if (x % 3) == 0 else 0.0)
    return x * x


def boom_on_3(x):
    if x == 3:
        raise ValueError("sample 3 is poison")
    return x


def burn(x):
    """CPU-bound mapper (~100 ms/call) for the multi-core-only speedup
    check — heavy enough that 48 calls (~5 s serial) amortize the
    spawn-context worker startup."""
    a = np.random.RandomState(x).rand(600, 600)
    for _ in range(20):
        a = a @ a.T
        a /= np.abs(a).max()
    return float(a[0, 0])


def die_hard(x):
    """Simulate a segfault/OOM-kill: the worker dies without posting any
    sentinel (os._exit skips all cleanup)."""
    import os
    if x == 2:
        os._exit(11)
    return x
