"""Native host-pipeline kernels (packer.cpp via ctypes): build, exact
equality with the Python fallbacks, and the packing round-trip under both
paths (the analog of the reference's CPU-vs-GPU equivalence oracles applied
to native-vs-Python)."""

import os
import subprocess

import numpy as np
import pytest

from paddle_tpu import native
from paddle_tpu.core import sequence as seq


def _have_gxx():
    try:
        subprocess.run(["g++", "--version"], capture_output=True, check=True)
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _have_gxx(), reason="no g++ in image")


def test_native_builds_and_loads():
    assert native.available(), "native lib failed to build with g++ present"


@pytest.mark.parametrize("seed", range(3))
def test_positions_native_equals_python(seed, monkeypatch):
    rng = np.random.RandomState(seed)
    segs = rng.randint(0, 4, size=(6, 32)).astype(np.int32)
    got = seq.positions_from_segments(segs)
    # force the Python path for the oracle
    monkeypatch.setenv("PADDLE_TPU_NO_NATIVE", "1")
    want = seq.positions_from_segments(segs)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("seed", range(3))
def test_first_fit_native_equals_python(seed, monkeypatch):
    rng = np.random.RandomState(seed)
    lengths = rng.randint(1, 20, size=50).astype(np.int64)
    order = np.argsort(-lengths, kind="stable")
    got = seq._first_fit(lengths, order, 24)
    monkeypatch.setenv("PADDLE_TPU_NO_NATIVE", "1")
    want = seq._first_fit(lengths, order, 24)
    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_array_equal(got[1], want[1])
    assert got[2] == want[2]


def test_pack_roundtrip_with_native():
    rng = np.random.RandomState(0)
    seqs = [rng.normal(size=(rng.randint(1, 12), 3)).astype(np.float32)
            for _ in range(20)]
    data, seg, pos = seq.pack_sequences(seqs, row_len=16)
    out = seq.unpack_sequences(data, seg)
    key = lambda a: tuple(np.round(a.ravel(), 5).tolist())
    assert sorted(map(key, out)) == sorted(map(key, seqs))
    # no token overlap and full coverage
    assert sum(len(s) for s in out) == sum(len(s) for s in seqs)


def test_disable_env_forces_python(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_NO_NATIVE", "1")
    assert native.lib() is None


def test_recordio_index_recovery_native_equals_python(tmp_path):
    """Lost .idx sidecar: the scanner rebuilds it (native fast path and
    Python fallback must agree exactly), and corruption is caught with the
    failing byte offset."""
    import json
    import os

    from paddle_tpu.data import recordio

    path = str(tmp_path / "data.rec")
    payloads = [bytes([i]) * (7 * i + 1) for i in range(12)]
    with recordio.RecordWriter(path) as w:
        for p in payloads:
            w.write(p)
    with open(path + ".idx") as f:
        want = json.load(f)["offsets"]
    os.remove(path + ".idx")

    # native path (if compiler available)
    got_native = recordio.recover_index(path, write=False)
    assert got_native == want

    # forced Python fallback
    os.environ["PADDLE_TPU_NO_NATIVE"] = "1"
    try:
        import paddle_tpu.native as native
        native._tried, native._lib = False, None
        got_py = recordio.recover_index(path, write=False)
    finally:
        del os.environ["PADDLE_TPU_NO_NATIVE"]
        native._tried, native._lib = False, None
    assert got_py == want

    # reading with a lost index works end-to-end
    assert [bytes(r) for r in recordio.read_records(path)] == payloads
    assert os.path.exists(path + ".idx")       # sidecar restored

    # corruption detection with byte offset
    with open(path, "r+b") as f:
        f.seek(want[3] + 9)
        f.write(b"\xff")
    os.remove(path + ".idx")
    with pytest.raises(IOError, match="corrupt"):
        recordio.recover_index(path, write=False)
