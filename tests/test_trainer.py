"""Trainer end-to-end tests: MNIST slice, events, evaluators, checkpoints,
and the 1-device vs 8-device equivalence check (the analog of the reference's
local-vs-remote comparison, gserver/tests/test_CompareSparse.cpp)."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu import data, optim
from paddle_tpu.data import datasets
from paddle_tpu.models import MnistMLP, LeNet
from paddle_tpu.nn import costs
from paddle_tpu.train import (Trainer, ClassificationError, EvaluatorSet,
                              checkpoint as ckpt, events as ev)


def mnist_batches(batch_size=64, n=512, split="train"):
    r = datasets.mnist(split, synthetic_n=n)
    return data.batched(
        data.map_readers(lambda s: {"x": s[0], "label": s[1]}, r), batch_size)


def make_trainer(model=None, mesh=None):
    return Trainer(
        model=model or MnistMLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3),
        mesh=mesh,
        evaluator=ClassificationError())


def test_mnist_end_to_end_slice(tmp_path):
    """The minimum end-to-end slice (SURVEY.md §7 stage 3): synthetic-MNIST
    LeNet-lite to high accuracy."""
    tr = make_trainer()
    reader = mnist_batches()
    tr.init(jax.random.PRNGKey(0), next(iter(reader())))
    seen = {"it": 0, "passes": []}

    def handler(e):
        if isinstance(e, ev.EndIteration):
            seen["it"] += 1
        elif isinstance(e, ev.EndPass):
            seen["passes"].append(e.metrics)

    tr.train(reader, num_passes=6, event_handler=handler,
             checkpoint_dir=str(tmp_path / "ckpt"))
    assert seen["it"] == 6 * 8  # 512/64 batches * passes
    final = seen["passes"][-1]
    # the synthetic set carries 10% label noise (Bayes ceiling ~0.90 without
    # memorization); learning the structure lands in the high 0.8s in 6
    # passes, a broken model stays near 0.1
    assert final["accuracy"] > 0.8, final
    # checkpoints written per pass, gc'd to keep_last=3
    dirs = sorted(os.listdir(tmp_path / "ckpt"))
    assert dirs == ["pass-00003", "pass-00004", "pass-00005"]


def test_evaluate_and_test_reader():
    tr = make_trainer()
    reader = mnist_batches(n=256)
    tr.init(jax.random.PRNGKey(0), next(iter(reader())))
    tr.train(reader, num_passes=4)
    cost, metrics = tr.evaluate(mnist_batches(n=256, split="train"))
    # 10% label noise: Bayes ceiling ~0.90 without memorization
    assert metrics["accuracy"] > 0.8
    assert cost < 1.0


def test_checkpoint_roundtrip_and_resume(tmp_path):
    tr = make_trainer()
    reader = mnist_batches(n=128)
    tr.init(jax.random.PRNGKey(0), next(iter(reader())))
    tr.train(reader, num_passes=2, checkpoint_dir=str(tmp_path))
    step_before = int(tr.train_state.step)
    p_before = jax.device_get(tr.train_state.params)

    tr2 = make_trainer()
    tr2.init(jax.random.PRNGKey(1), next(iter(reader())))  # different init
    tr2.restore(str(tmp_path))
    assert int(tr2.train_state.step) == step_before
    p_after = jax.device_get(tr2.train_state.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), p_before, p_after)
    # resume skips completed passes
    tr3 = make_trainer()
    tr3.init(jax.random.PRNGKey(2), next(iter(reader())))
    tr3.train(reader, num_passes=2, checkpoint_dir=str(tmp_path), resume=True)
    assert int(tr3.train_state.step) == step_before  # nothing re-run


def test_checkpoint_crc_detects_corruption(tmp_path):
    tree = {"params": {"w": np.ones((2, 2))}, "step": np.asarray(5)}
    d = ckpt.save_checkpoint(str(tmp_path), 0, tree)
    # corrupt the file
    path = os.path.join(d, "params.npz")
    with open(path, "r+b") as f:
        f.seek(30)
        f.write(b"\xff\xff")
    with pytest.raises(IOError, match="crc"):
        ckpt.load_checkpoint(str(tmp_path))


def test_single_vs_multichip_equivalence():
    """1-device vs 8-device data parallel must produce the same training
    trajectory (the reference's local-vs-remote oracle,
    test_CompareSparse.cpp:144)."""
    devices = jax.devices()
    assert len(devices) >= 8, "conftest must force 8 CPU devices"
    reader = mnist_batches(batch_size=64, n=256)
    results = []
    for mesh in (pt.make_mesh({"data": 1}, devices=devices[:1]),
                 pt.make_mesh({"data": 8}, devices=devices[:8])):
        tr = make_trainer(mesh=mesh)
        tr.init(jax.random.PRNGKey(0), next(iter(reader())))
        tr.train(reader, num_passes=1)
        results.append((float(jax.device_get(
            optim.global_norm(tr.train_state.params))),
            int(tr.train_state.step)))
    norm1, steps1 = results[0]
    norm8, steps8 = results[1]
    assert steps1 == steps8
    np.testing.assert_allclose(norm1, norm8, rtol=1e-4)


def test_weighted_loss_path():
    tr = Trainer(
        model=MnistMLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.sgd(0.1))
    r = datasets.mnist("train", synthetic_n=64)

    def wreader():
        for b in data.batched(
                data.map_readers(lambda s: {"x": s[0], "label": s[1]}, r),
                32)():
            b["weight"] = np.ones_like(b["label"], np.float32)
            yield b

    tr.init(jax.random.PRNGKey(0), next(iter(wreader())))
    tr.train(wreader, num_passes=1)
    assert int(tr.train_state.step) == 2


def test_checkpoint_loads_collection_keyed_manifest(tmp_path):
    """Manifests from the earlier format keyed files by collection name
    ('params') rather than filename ('params.npz'); both must load."""
    import json
    import os
    from paddle_tpu.train import checkpoint as ckpt
    tree = {"params": {"w": np.arange(4.0)}}
    ckpt.save_checkpoint(str(tmp_path), 0, tree)
    d = ckpt.pass_dir(str(tmp_path), 0)
    with open(os.path.join(d, "manifest.json")) as f:
        man = json.load(f)
    man["files"] = {k[:-len(".npz")] if k.endswith(".npz") else k: v
                    for k, v in man["files"].items()}
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(man, f)
    out = ckpt.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(out["params"]["w"], np.arange(4.0))


def test_manifest_missing_entry_and_collision_errors(tmp_path):
    """A manifest entry whose file is gone must raise a clear integrity
    error naming the entry, and legacy-name normalisation must not silently
    merge colliding keys (ADVICE r2)."""
    import json
    import os
    from paddle_tpu.train import checkpoint as ckpt
    tree = {"params": {"w": np.arange(4.0)}, "step": np.asarray(1)}
    ckpt.save_checkpoint(str(tmp_path), 0, tree)
    d = ckpt.pass_dir(str(tmp_path), 0)
    with open(os.path.join(d, "manifest.json")) as f:
        man = json.load(f)
    # missing file
    man2 = dict(man)
    man2["files"] = {**man["files"], "ghost": {"crc32": 0}}
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(man2, f)
    with pytest.raises(IOError, match="ghost"):
        ckpt.verify_manifest(d)
    # collision: 'params' and 'params.npz' both normalise to params.npz
    man3 = dict(man)
    man3["files"] = {**man["files"], "params": {"crc32": 0}}
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(man3, f)
    with pytest.raises(IOError, match="collide"):
        ckpt.verify_manifest(d)


def test_resume_warns_on_nondeterministic_reader(tmp_path, caplog):
    """Mid-pass resume replays the reader; if the replayed batch at the
    checkpointed position differs from the recorded fingerprint the trainer
    must warn instead of silently training on a different remainder."""
    import logging
    rng_batches = []

    def shuffled_reader(seed=[0]):
        def r():
            rng = np.random.RandomState(seed[0])
            seed[0] += 1          # different every replay => nondeterministic
            for _ in range(4):
                x = rng.normal(size=(32, 784)).astype(np.float32)
                y = rng.randint(0, 10, 32).astype(np.int32)
                yield {"x": x, "label": y}
        return r

    reader = shuffled_reader()
    tr = make_trainer()
    tr.init(jax.random.PRNGKey(0), next(iter(reader())))
    tr.train(reader, num_passes=1, checkpoint_dir=str(tmp_path),
             saving_period=2, log_period=0)
    # wipe the completed-pass marker so resume enters mid-pass replay
    ckpt.save_checkpoint(
        str(tmp_path), 0,
        {**tr.train_state.as_dict(),
         "iter": {"pass": 0, "next_batch": 2, "completed": 0,
                  "batch_crc": 12345}})   # fingerprint that cannot match
    tr2 = make_trainer()
    tr2.init(jax.random.PRNGKey(1), next(iter(reader())))
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.trainer"):
        tr2.train(reader, num_passes=1, checkpoint_dir=str(tmp_path),
                  resume=True, log_period=0)
    assert any("nondeterministic" in r.message for r in caplog.records)


def test_kill_resume_reproduces_uninterrupted_run(tmp_path):
    """Mid-pass checkpoint + resume: interrupting training and resuming from
    the saved iterator position must reproduce the uninterrupted run's final
    parameters exactly (reference capability: Go master task-queue recovery +
    --saving_period_by_batches; deterministic single-controller replay)."""
    reader = mnist_batches(n=256)      # 4 batches/pass, deterministic

    # --- uninterrupted run: 2 passes
    tr_a = make_trainer()
    tr_a.init(jax.random.PRNGKey(0), next(iter(reader())))
    tr_a.train(reader, num_passes=2, log_period=0)
    want = jax.device_get(tr_a.train_state.params)
    want_step = int(tr_a.train_state.step)

    # --- interrupted run: same init, killed mid-pass-1 after batch 2
    class Killed(Exception):
        pass

    tr_b = make_trainer()
    tr_b.init(jax.random.PRNGKey(0), next(iter(reader())))

    def killer(e):
        if isinstance(e, ev.EndIteration) and e.pass_id == 1 \
                and e.batch_id == 1:
            raise Killed()          # dies AFTER the saving_period checkpoint

    with pytest.raises(Killed):
        tr_b.train(reader, num_passes=2, checkpoint_dir=str(tmp_path),
                   saving_period=2, log_period=0, event_handler=killer)

    # --- fresh process: restore + finish; must land on the same params
    tr_c = make_trainer()
    tr_c.init(jax.random.PRNGKey(7), next(iter(reader())))  # different init
    tr_c.train(reader, num_passes=2, checkpoint_dir=str(tmp_path),
               saving_period=2, log_period=0, resume=True)
    got = jax.device_get(tr_c.train_state.params)
    assert int(tr_c.train_state.step) == want_step
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        want, got)
