"""Layer library tests: shape/oracle checks vs numpy + numeric gradient checks
vs jax.grad (the analog of the reference's test_LayerGrad.cpp harness)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.nn as nn
from paddle_tpu.nn import activations


def numeric_grad_check(mod, vs, *args, eps=1e-3, tol=2e-2):
    """Perturb params, compare numeric vs autodiff grads of sum(out)."""
    def loss(params):
        return jnp.sum(mod.apply({"params": params, "state": vs.get("state", {})},
                                 *args) ** 2)

    g = jax.grad(loss)(vs["params"])
    flat_p, tree = jax.tree_util.tree_flatten(vs["params"])
    flat_g = jax.tree_util.tree_leaves(g)
    for pi, (p, ag) in enumerate(zip(flat_p, flat_g)):
        it = np.ndindex(*p.shape) if p.ndim else [()]
        for idx in list(it)[:3]:  # spot-check first few entries
            dp = np.zeros_like(np.asarray(p))
            dp[idx] = eps
            plus = jax.tree_util.tree_unflatten(
                tree, [q + dp if i == pi else q for i, q in enumerate(flat_p)])
            minus = jax.tree_util.tree_unflatten(
                tree, [q - dp if i == pi else q for i, q in enumerate(flat_p)])
            num = (loss(plus) - loss(minus)) / (2 * eps)
            np.testing.assert_allclose(np.asarray(ag)[idx], num, rtol=tol,
                                       atol=tol)


def test_linear_matches_numpy(rng):
    m = nn.Linear(7, act="tanh")
    x = jax.random.normal(rng, (4, 5))
    vs = m.init(rng, x)
    w = np.asarray(vs["params"]["Linear_0"]["w"])
    b = np.asarray(vs["params"]["Linear_0"]["b"])
    want = np.tanh(np.asarray(x) @ w + b)
    np.testing.assert_allclose(np.asarray(m.apply(vs, x)), want, atol=1e-5)
    numeric_grad_check(m, vs, x)


def test_embedding_oov_and_grad(rng):
    m = nn.Embedding(10, 4)
    ids = jnp.array([[0, 9, -1], [3, 3, 10]])
    vs = m.init(rng, ids)
    out = m.apply(vs, ids)
    assert out.shape == (2, 3, 4)
    np.testing.assert_array_equal(np.asarray(out[0, 2]), np.zeros(4))
    np.testing.assert_array_equal(np.asarray(out[1, 2]), np.zeros(4))
    np.testing.assert_allclose(out[1, 0], out[1, 1])


def test_conv2d_matches_scipy(rng):
    m = nn.Conv2D(3, kernel=3, padding="VALID")
    x = jax.random.normal(rng, (2, 8, 8, 2))
    vs = m.init(rng, x)
    out = m.apply(vs, x)
    assert out.shape == (2, 6, 6, 3)
    # oracle: direct correlation
    w = np.asarray(vs["params"]["Conv2D_0"]["w"])
    b = np.asarray(vs["params"]["Conv2D_0"]["b"])
    xn = np.asarray(x)
    want = np.zeros((2, 6, 6, 3), np.float32)
    for n in range(2):
        for i in range(6):
            for j in range(6):
                patch = xn[n, i:i + 3, j:j + 3, :]
                want[n, i, j] = np.tensordot(patch, w, axes=3) + b
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-4)


def test_conv_grad(rng):
    m = nn.Conv2D(2, kernel=2, padding="SAME")
    x = jax.random.normal(rng, (1, 4, 4, 2))
    vs = m.init(rng, x)
    numeric_grad_check(m, vs, x)


def test_pool(rng):
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    m = nn.Pool2D("max", 2)
    vs = m.init(rng, x)
    out = np.asarray(m.apply(vs, x))[0, :, :, 0]
    np.testing.assert_array_equal(out, [[5, 7], [13, 15]])
    a = nn.Pool2D("avg", 2)
    out2 = np.asarray(a.apply(a.init(rng, x), x))[0, :, :, 0]
    np.testing.assert_allclose(out2, [[2.5, 4.5], [10.5, 12.5]])


def test_batchnorm_train_and_eval(rng):
    m = nn.BatchNorm(momentum=0.5)
    x = jax.random.normal(rng, (64, 3)) * 4.0 + 2.0
    vs = m.init(rng, x, train=True)
    out, new = m.apply(vs, x, train=True, mutable=("state",))
    np.testing.assert_allclose(np.asarray(out).mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out).std(0), 1.0, atol=1e-2)
    # eval mode uses running stats, no mutation needed
    out_eval = m.apply({"params": vs["params"], "state": new["state"]}, x)
    assert out_eval.shape == x.shape


def test_batchnorm_custom_vjp_matches_autodiff(rng):
    """The hand-written training-mode BN backward (closed-form total
    derivative, 2 reductions) must agree with plain autodiff through an
    explicit mean/var formulation — exact oracle, f32-epsilon tight."""
    from paddle_tpu.nn.layers import _bn_train_norm
    eps = 1e-5
    x = jax.random.normal(rng, (8, 4, 5)) * 2.0 + 1.0
    gamma = jax.random.normal(jax.random.PRNGKey(1), (5,))
    beta = jax.random.normal(jax.random.PRNGKey(2), (5,))

    def stats(x):
        axes = (0, 1)
        n = x.size // x.shape[-1]
        mean = jnp.sum(x, axes) / n
        var = jnp.maximum(jnp.sum(x * x, axes) / n - mean * mean, 0.0)
        return mean, jax.lax.rsqrt(var + eps)

    def explicit(x, gamma, beta):
        mean, inv = stats(x)
        return (x - mean) * inv * gamma + beta

    def custom(x, gamma, beta):
        mean, inv = stats(x)
        return _bn_train_norm(x, mean, inv, gamma, beta)

    def loss(f, x, g, b):
        return jnp.sum(jnp.sin(f(x, g, b)) ** 2)

    ge = jax.grad(lambda *a: loss(explicit, *a), argnums=(0, 1, 2))(
        x, gamma, beta)
    gc = jax.grad(lambda *a: loss(custom, *a), argnums=(0, 1, 2))(
        x, gamma, beta)
    for a, b in zip(ge, gc):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-5)


def test_layernorm(rng):
    m = nn.LayerNorm()
    x = jax.random.normal(rng, (5, 16)) * 3 + 1
    vs = m.init(rng, x)
    out = np.asarray(m.apply(vs, x))
    np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1, atol=1e-2)


def test_dropout_modes(rng):
    m = nn.Dropout(0.5)
    x = jnp.ones((100, 100))
    vs = m.init(rng, x)
    out_eval = m.apply(vs, x)
    np.testing.assert_array_equal(np.asarray(out_eval), np.asarray(x))
    out_train = np.asarray(
        m.apply(vs, x, train=True, rngs={"dropout": rng}))
    frac = (out_train == 0).mean()
    assert 0.4 < frac < 0.6
    kept = out_train[out_train != 0]
    np.testing.assert_allclose(kept, 2.0)


def test_maxout():
    m = nn.Maxout(2)
    x = jnp.array([[1.0, 5.0, 2.0, 0.0]])
    vs = m.init(jax.random.PRNGKey(0), x)
    np.testing.assert_array_equal(np.asarray(m.apply(vs, x)), [[5.0, 2.0]])


def test_cos_sim(rng):
    m = nn.CosSim(scale=5.0)
    a = jax.random.normal(rng, (3, 8))
    vs = m.init(rng, a, a)
    np.testing.assert_allclose(np.asarray(m.apply(vs, a, a)), 5.0, rtol=1e-5)


def test_context_projection():
    m = nn.ContextProjection(context_len=3, context_start=-1)
    x = jnp.arange(6.0).reshape(1, 3, 2)
    vs = m.init(jax.random.PRNGKey(0), x)
    out = np.asarray(m.apply(vs, x))
    assert out.shape == (1, 3, 6)
    # t=0: [zeros, x0, x1]
    np.testing.assert_array_equal(out[0, 0], [0, 0, 0, 1, 2, 3])
    # t=2: [x1, x2, zeros]
    np.testing.assert_array_equal(out[0, 2], [2, 3, 4, 5, 0, 0])


def test_mixed_layer(rng):
    m = nn.MixedLayer([nn.FullMatrixProjection(6), nn.IdentityProjection()],
                      act="relu")
    a = jax.random.normal(rng, (2, 4))
    b = jax.random.normal(rng, (2, 6))
    vs = m.init(rng, a, b)
    out = m.apply(vs, a, b)
    assert out.shape == (2, 6)
    numeric_grad_check(m, vs, a, b)


def test_block_expand(rng):
    m = nn.BlockExpand(block=2, stride=2)
    x = jax.random.normal(rng, (1, 4, 4, 3))
    vs = m.init(rng, x)
    assert m.apply(vs, x).shape == (1, 4, 12)


def test_multiplex():
    m = nn.Multiplex()
    a = jnp.zeros((3, 2))
    b = jnp.ones((3, 2))
    idx = jnp.array([0, 1, 0])
    vs = m.init(jax.random.PRNGKey(0), idx, a, b)
    out = np.asarray(m.apply(vs, idx, a, b))
    np.testing.assert_array_equal(out[:, 0], [0, 1, 0])


def test_activation_registry():
    x = jnp.array([-2.0, 0.5, 30.0])
    assert np.asarray(activations.get("brelu")(x)).tolist() == [0.0, 0.5, 24.0]
    np.testing.assert_allclose(activations.get("stanh")(jnp.zeros(1)), 0.0)
    np.testing.assert_allclose(
        np.asarray(activations.get("softsign")(jnp.array([1.0]))), [0.5])
    with pytest.raises(KeyError):
        activations.get("nope")


def test_sequence_softmax():
    x = jnp.array([[1.0, 1.0, 1.0, 9.0]])
    out = np.asarray(activations.sequence_softmax(x, lengths=jnp.array([3])))
    np.testing.assert_allclose(out[0, :3], 1 / 3, rtol=1e-5)
    assert out[0, 3] == 0


def test_stem_s2d_lowering_matches_direct_conv(rng):
    """The 7x7/2 SAME tiny-C_in stem lowers through the exact
    space-to-depth rewrite (layers.py Conv2D.forward); it must match the
    direct lax conv to float roundoff on odd AND even-channel inputs and
    non-224 (even) sizes."""
    from jax import lax
    for hw, cin in ((56, 3), (48, 4)):
        m = nn.Conv2D(16, kernel=7, stride=2, padding="SAME",
                      use_bias=False)
        x = jax.random.normal(jax.random.fold_in(rng, hw),
                              (2, hw, hw, cin), jnp.float32)
        v = m.init(rng, x)
        got = m.apply(v, x)
        w = v["params"]["Conv2D_0"]["w"]
        want = lax.conv_general_dilated(
            x, w, (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        # the numeric check alone is vacuous (both branches compute the
        # same function): assert the s2d lowering actually FIRED — the
        # program must carry the stride-1 pad-(1,2) conv, not the 7x7/2
        hlo = jax.jit(lambda xx: m.apply(v, xx)).lower(x).as_text()
        assert "pad = [[1, 2], [1, 2]]" in hlo, \
            "s2d stem lowering did not fire"
        assert "stride = [2, 2]" not in hlo, \
            "direct 7x7/2 conv still present"
