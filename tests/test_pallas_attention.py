"""Pallas fused attention vs the reference softmax oracle (interpret mode on
the CPU harness; the same kernel compiles on TPU — see the verify drive)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nn.pallas_attention import (flash_attention,
                                            reference_attention)


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).normal(
        size=shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 2, 128, 32), (2, 1, 256, 16)])
def test_flash_matches_reference(shape, causal):
    B, H, T, D = shape
    q, k, v = (_rand(shape, s) for s in range(3))
    got = flash_attention(q, k, v, None, causal, None, 64, 64, True)
    want = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_uneven_blocks():
    # block sizes clamp to T when T is smaller
    q, k, v = (_rand((1, 1, 64, 8), s) for s in range(3))
    got = flash_attention(q, k, v, None, False, None, 128, 128, True)
    want = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_gradients_match_reference():
    q, k, v = (_rand((1, 2, 128, 16), s) for s in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True, None, 64, 64, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_causality_enforced():
    # output at position t must not depend on keys/values after t
    q, k, v = (_rand((1, 1, 128, 8), s) for s in range(3))
    out1 = flash_attention(q, k, v, None, True, None, 64, 64, True)
    v2 = v.at[:, :, 100:].set(99.0)
    k2 = k.at[:, :, 100:].set(-7.0)
    out2 = flash_attention(q, k2, v2, None, True, None, 64, 64, True)
    np.testing.assert_allclose(np.asarray(out1[:, :, :100]),
                               np.asarray(out2[:, :, :100]), rtol=1e-5)
    assert not np.allclose(np.asarray(out1[:, :, 100:]),
                           np.asarray(out2[:, :, 100:]))


def test_mha_flash_matches_xla_path():
    from paddle_tpu.nn.attention import MultiHeadAttention
    x = _rand((2, 128, 32), 7)
    plain = MultiHeadAttention(num_heads=4)
    flash = MultiHeadAttention(num_heads=4, use_flash=True)
    p = plain.init(jax.random.PRNGKey(0), x)
    y1 = plain.apply(p, x, causal=True)
    y2 = flash.apply(p, x, causal=True)   # same params, flash path
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=2e-3, atol=2e-4)
    with pytest.raises(ValueError):
        flash.apply(p, x, mask=jnp.ones((2, 128, 128)))


def test_mha_flash_guards_and_block_pick():
    from paddle_tpu.nn.attention import MultiHeadAttention
    flash = MultiHeadAttention(num_heads=2, use_flash=True)
    x = _rand((1, 96, 16), 3)          # 96 -> block 32
    p = flash.init(jax.random.PRNGKey(0), x)
    y = flash.apply(p, x, causal=True)
    assert y.shape == (1, 96, 16)
    kv = _rand((1, 96, 16), 4)
    with pytest.raises(ValueError, match="self-attention"):
        flash.apply(p, x, kv)
    bad = _rand((1, 67, 16), 5)        # prime-ish length: must be padded
    with pytest.raises(ValueError, match="divisible"):
        flash.init(jax.random.PRNGKey(0), bad)


def test_flash_gradients_noncausal_and_vmapped():
    # non-causal grads vs reference, plus the custom_vjp under vmap
    q, k, v = (_rand((2, 2, 128, 16), s) for s in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, False, None, 64, 64, True)
                       ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, False) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)
    # the custom_vjp must batch correctly under vmap (extra leading dim)
    qb, kb, vb = (jnp.stack([t, t * 0.5]) for t in (q, k, v))
    gv = jax.vmap(jax.grad(loss_flash))(qb, kb, vb)
    g0 = jax.grad(loss_flash)(q, k, v)
    np.testing.assert_allclose(np.asarray(gv[0]), np.asarray(g0),
                               rtol=2e-3, atol=2e-4)


def test_flash_bf16_forward_backward():
    q, k, v = (_rand((1, 1, 128, 16), s).astype(jnp.bfloat16)
               for s in range(3))
    out = flash_attention(q, k, v, None, True, None, 64, 64, True)
    assert out.dtype == jnp.bfloat16
    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, None, True, None, 64, 64, True)
        .astype(jnp.float32)))(q)
    assert g.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(g, np.float32)).all()


def test_flash_vjp_passes_whole_model_gradcheck():
    """The checkgrad utility validates the hand-written Pallas VJP."""
    from paddle_tpu.utils.gradcheck import check_gradients
    q, k, v = (_rand((1, 1, 64, 8), s) for s in range(3))

    def loss_fn(p):
        return jnp.sum(flash_attention(p["q"], p["k"], p["v"], None, True, None,
                                       32, 32, True) ** 2)

    check_gradients(loss_fn, {"q": q, "k": k, "v": v}, num_directions=2)
