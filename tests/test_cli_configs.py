"""The acceptance-set config scripts train through the CLI with no user
code — VERDICT r2 item 7 (reference workflow: ``paddle_trainer
--config=trainer_config.py``; configs in ``configs/`` mirror
``v1_api_demo/sequence_tagging/linear_crf.py``, the seqToseq attention
config, and the SSD config family)."""

import os

import numpy as np
import pytest

from paddle_tpu.train import cli

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, extra=()):
    flags = cli.parse_flags(
        cli.TrainCliFlags,
        ["--config", os.path.join(_REPO, "configs", script),
         "--log_period", "0", *extra])
    return cli.run(flags)


def test_crf_tagging_config_trains():
    metrics = _run("sequence_tagging_crf.py")
    assert np.isfinite(metrics["mean_cost"])
    # the tag rule is deterministic: 3 passes must cut the NLL sharply
    first = _run("sequence_tagging_crf.py", ["--num_passes", "1"])
    assert metrics["mean_cost"] < first["mean_cost"]


def test_seq2seq_attention_config_trains():
    metrics = _run("seq2seq_attention.py")
    assert np.isfinite(metrics["mean_cost"])


def test_ssd_detection_config_trains():
    metrics = _run("ssd_detection.py")
    assert np.isfinite(metrics["mean_cost"])


def test_config_script_missing_outputs_rejected(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("from paddle_tpu.config_helpers import *\n"
                   "settings(batch_size=4)\n"
                   "def train_reader(bs):\n"
                   "    def r():\n"
                   "        yield {}\n"
                   "    return r\n")
    flags = cli.parse_flags(cli.TrainCliFlags, ["--config", str(bad)])
    with pytest.raises(SystemExit, match="outputs"):
        cli.run(flags)


def test_cli_job_modes():
    """--job test / checkgrad / time — the reference trainer's non-train
    modes (TrainerMain.cpp:25, TrainerBenchmark.cpp)."""
    t = _run("sequence_tagging_crf.py",
             ["--job", "time", "--time_batches", "3", "--use_bf16", "0"])
    assert t["batches"] == 3 and t["ms_per_batch"] > 0
    g = _run("sequence_tagging_crf.py", ["--job", "checkgrad",
                                         "--use_bf16", "0"])
    assert g["checkgrad_ok"] == 1
    e = _run("sequence_tagging_crf.py", ["--job", "test", "--use_bf16", "0"])
    assert np.isfinite(e["test_cost"])


def test_mnist_mlp_config_with_evaluator():
    """classification_cost + evaluator surface: the light_mnist config
    trains from script with classification-error computed in-step, and the
    test_reader feeds --job test."""
    m = _run("mnist_mlp.py", ["--use_bf16", "0"])
    assert "classification_error" in m
    assert 0.0 <= m["classification_error"] <= 1.0
    assert m["classification_error"] < 0.5      # separable synthetic task
    t = _run("mnist_mlp.py", ["--job", "test", "--use_bf16", "0"])
    assert np.isfinite(t["test_cost"])
