"""Optimizer tests: convergence on a quadratic, oracle updates, schedules,
composition — the analog of the reference's optimizer unit tests
(paddle/parameter/tests, paddle/optimizer/parameter_optimizer_test.cc)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import optim
from paddle_tpu.optim import schedules as S


def quad_loss(p):
    return 0.5 * jnp.sum(p["w"] ** 2) + 0.5 * jnp.sum((p["b"] - 1.0) ** 2)


PARAMS = {"w": jnp.array([2.0, -3.0]), "b": jnp.array([0.0])}


@pytest.mark.parametrize("make", [
    lambda: optim.sgd(0.1),
    lambda: optim.momentum(0.05, 0.9),
    lambda: optim.momentum(0.05, 0.9, nesterov=True),
    lambda: optim.adagrad(0.5),
    lambda: optim.decayed_adagrad(0.3),
    lambda: optim.adadelta(rho=0.9, eps=1e-2, lr=1.0),
    lambda: optim.rmsprop(0.05),
    lambda: optim.rmsprop(0.05, momentum_coef=0.9, centered=False),
    lambda: optim.adam(0.2),
    lambda: optim.adamax(0.2),
    lambda: optim.ftrl(0.5),
    lambda: optim.lamb(0.05),
])
def test_converges_on_quadratic(make):
    opt = make()
    params = PARAMS
    state = opt.init(params)
    l0 = float(quad_loss(params))

    @jax.jit
    def step_fn(params, state, i):
        g = jax.grad(quad_loss)(params)
        upd, state = opt.update(g, state, params, i)
        return optim.apply_updates(params, upd), state

    for i in range(300):
        params, state = step_fn(params, state, jnp.asarray(i))
    assert float(quad_loss(params)) < 0.05 * l0, type(opt)


def test_sgd_oracle():
    opt = optim.sgd(0.1)
    g = {"w": jnp.array([1.0, 2.0])}
    p = {"w": jnp.array([1.0, 1.0])}
    upd, _ = opt.update(g, opt.init(p), p, 0)
    np.testing.assert_allclose(np.asarray(upd["w"]), [-0.1, -0.2], rtol=1e-6)


def test_adam_oracle_first_step():
    opt = optim.adam(0.1, b1=0.9, b2=0.999, eps=1e-8)
    g = {"w": jnp.array([0.5])}
    p = {"w": jnp.array([0.0])}
    upd, st = opt.update(g, opt.init(p), p, 0)
    # first step: mhat = g, vhat = g^2 -> update = -lr * g/(|g|+eps) ~ -lr*sign
    np.testing.assert_allclose(np.asarray(upd["w"]), [-0.1], rtol=1e-4)


def test_clipping_global_norm():
    clip = optim.clip_by_global_norm(1.0)
    g = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}  # norm 5
    upd, _ = clip.update(g, clip.init(g), g, 0)
    np.testing.assert_allclose(optim.global_norm(upd), 1.0, rtol=1e-5)
    # under the limit: unchanged
    g2 = {"a": jnp.array([0.3]), "b": jnp.array([0.4])}
    upd2, _ = clip.update(g2, clip.init(g2), g2, 0)
    np.testing.assert_allclose(np.asarray(upd2["a"]), [0.3], rtol=1e-6)


def test_chain_clip_decay_rule():
    opt = optim.chain(optim.clip_by_value(0.5), optim.weight_decay(0.1),
                      optim.sgd(1.0))
    p = {"w": jnp.array([2.0])}
    g = {"w": jnp.array([10.0])}
    st = opt.init(p)
    upd, _ = opt.update(g, st, p, 0)
    # clip to 0.5, add 0.1*2 decay, sgd lr 1 -> -(0.5+0.2)
    np.testing.assert_allclose(np.asarray(upd["w"]), [-0.7], rtol=1e-6)


def test_l1_decay_sign():
    opt = optim.chain(optim.l1_decay(0.5), optim.sgd(1.0))
    p = {"w": jnp.array([2.0, -2.0])}
    g = {"w": jnp.zeros(2)}
    upd, _ = opt.update(g, opt.init(p), p, 0)
    np.testing.assert_allclose(np.asarray(upd["w"]), [-0.5, 0.5], rtol=1e-6)


def test_schedules():
    assert float(S.constant()(100)) == 1.0
    np.testing.assert_allclose(float(S.poly(1.0, 1.0)(1)), 0.5)
    np.testing.assert_allclose(float(S.exponential(0.5, 10)(10)), 0.5)
    np.testing.assert_allclose(float(S.discexp(0.5, 10)(19)), 0.5)
    np.testing.assert_allclose(float(S.discexp(0.5, 10)(20)), 0.25)
    np.testing.assert_allclose(float(S.linear(0.1, 0.2)(5)), 0.5)
    np.testing.assert_allclose(float(S.linear(0.1, 0.2)(100)), 0.2)
    m = S.manual([10, 20], [1.0, 0.5, 0.1])
    np.testing.assert_allclose([float(m(5)), float(m(15)), float(m(25))],
                               [1.0, 0.5, 0.1], rtol=1e-6)
    np.testing.assert_allclose(float(S.warmup_linear(10)(4)), 0.5)
    np.testing.assert_allclose(float(S.cosine_decay(100)(100)), 0.0, atol=1e-6)
    c = S.chain(S.warmup_linear(10), S.constant())
    np.testing.assert_allclose(float(c(4)), 0.5)


def test_lr_schedule_in_optimizer():
    opt = optim.sgd(1.0, schedule=S.manual([5], [1.0, 0.1]))
    g = {"w": jnp.array([1.0])}
    p = {"w": jnp.array([0.0])}
    upd0, _ = opt.update(g, (), p, 0)
    upd9, _ = opt.update(g, (), p, 9)
    np.testing.assert_allclose(np.asarray(upd0["w"]), [-1.0])
    np.testing.assert_allclose(np.asarray(upd9["w"]), [-0.1], rtol=1e-5)


def test_ema_average():
    ema = optim.polyak_average(0.5)
    p = {"w": jnp.array([0.0])}
    avg = ema.init(p)
    avg = ema.update(avg, {"w": jnp.array([2.0])})
    np.testing.assert_allclose(np.asarray(avg["w"]), [1.0])


def test_optimizer_state_is_pytree():
    opt = optim.adam(0.1)
    p = {"w": jnp.zeros((3, 3))}
    st = opt.init(p)
    leaves = jax.tree_util.tree_leaves(st)
    assert all(l.shape == (3, 3) for l in leaves)
    # jit-compatible
    @jax.jit
    def f(st):
        return jax.tree_util.tree_map(lambda x: x + 1, st)
    f(st)


def test_static_pruning_mask_sticks():
    """StaticPruningHook analog: bottom-|w| weights zero at the first update
    and stay exactly zero while survivors train."""
    import jax.numpy as jnp
    from paddle_tpu.optim.optimizers import sgd, static_pruning
    opt = static_pruning(sgd(0.1), sparsity=0.5)
    p = {"w": jnp.asarray(np.arange(1.0, 11.0, dtype=np.float32))}
    st = opt.init(p)
    g = {"w": jnp.ones(10)}
    p1, st = opt.apply(g, st, p, jnp.asarray(0))
    np.testing.assert_allclose(np.asarray(p1["w"][:5]), 0.0)
    np.testing.assert_allclose(np.asarray(p1["w"][5:]),
                               np.arange(6.0, 11.0) - 0.1, rtol=1e-6)
    p2, st = opt.apply(g, st, p1, jnp.asarray(1))
    np.testing.assert_allclose(np.asarray(p2["w"][:5]), 0.0)
    assert (np.asarray(p2["w"][5:]) < np.asarray(p1["w"][5:])).all()


def test_gradient_checker_passes_and_catches_bugs():
    """--job=checkgrad analog: passes on a real model loss, fails on a
    deliberately wrong custom gradient."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.nn import costs
    from paddle_tpu.utils.gradcheck import check_gradients

    model = MnistMLP()
    x = jnp.asarray(np.random.RandomState(0).normal(
        size=(4, 28, 28, 1)).astype(np.float32))
    y = jnp.asarray(np.array([1, 2, 3, 4], np.int32))
    v = model.init(jax.random.PRNGKey(0), x)

    def loss_fn(params):
        out = model.apply({"params": params, "state": v.get("state", {})}, x)
        return jnp.mean(costs.softmax_cross_entropy(out, y))

    # gradient-direction probe is exact to f32 noise; random probes looser
    check_gradients(loss_fn, v["params"], num_directions=1)

    # a wrong custom vjp must be caught
    @jax.custom_vjp
    def bad_square(t):
        return t * t
    bad_square.defvjp(lambda t: (t * t, t),
                      lambda t, g: (3.0 * t * g,))   # wrong: should be 2t

    import pytest
    with pytest.raises(AssertionError, match="gradient check failed"):
        check_gradients(lambda p: jnp.sum(bad_square(p["w"])),
                        {"w": jnp.asarray(np.ones(4, np.float32))},
                        num_directions=2)


def test_static_pruning_zero_init_tensor_not_wiped():
    """Tie-handling: a zero-initialized tensor must lose exactly the
    requested fraction, not everything."""
    import jax.numpy as jnp
    from paddle_tpu.optim.optimizers import sgd, static_pruning
    opt = static_pruning(sgd(0.1), sparsity=0.5)
    p = {"b": jnp.zeros(10)}
    st = opt.init(p)
    mask = np.asarray(st.mask["b"])
    assert mask.sum() == 5          # exactly half survives despite all ties
