"""Packed-sequence (segment-id) masking through every fast attention path.

The framework's variable-length contract is packing + segment ids
(``core.sequence``, replacing the reference's never-padded
``Argument::sequenceStartPositions`` ragged batches, Argument.h:84-93).
These tests pin that each fast path — Pallas flash, ring, Ulysses — consumes
that contract and matches the XLA dense-mask oracle, forward and backward.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import parallel
from paddle_tpu.core.sequence import pack_sequences
from paddle_tpu.nn.pallas_attention import flash_attention, reference_attention


@pytest.fixture
def nprng():
    return np.random.RandomState(0)


def _packed_segments(nprng, B, T):
    """Random packed layout: each row packs 2-4 variable-length sequences
    plus trailing padding (ids 1-based, 0 = pad)."""
    seg = np.zeros((B, T), np.int32)
    for b in range(B):
        pos, sid = 0, 1
        while pos < T - 2 and sid <= 4:
            L = int(nprng.randint(2, max(3, T // 3)))
            L = min(L, T - pos)
            seg[b, pos:pos + L] = sid
            pos += L
            sid += 1
        # leave the tail (if any) as padding on some rows
        if nprng.rand() < 0.5 and pos < T:
            seg[b, pos:] = sid
    return jnp.asarray(seg)


def _rand(nprng, shape):
    return jnp.asarray(nprng.normal(size=shape).astype(np.float32))


def _valid_rows(seg):
    return np.asarray(seg) > 0


# ------------------------------------------------------------------- flash

@pytest.mark.parametrize("causal", [False, True])
def test_flash_segments_match_oracle(nprng, causal):
    B, H, T, D = 2, 2, 128, 8
    q, k, v = (_rand(nprng, (B, H, T, D)) for _ in range(3))
    seg = _packed_segments(nprng, B, T)
    got = flash_attention(q, k, v, seg, causal, None, 32, 32, True)
    ref = reference_attention(q, k, v, causal=causal, segments=seg)
    valid = _valid_rows(seg)                       # [B, T]
    mask = valid[:, None, :, None]
    np.testing.assert_allclose(np.asarray(got) * mask, np.asarray(ref) * mask,
                               rtol=2e-5, atol=2e-5)


def test_flash_segments_grads_match_oracle(nprng):
    B, H, T, D = 1, 2, 64, 8
    q, k, v = (_rand(nprng, (B, H, T, D)) for _ in range(3))
    seg = _packed_segments(nprng, B, T)
    w = jnp.asarray(_valid_rows(seg), jnp.float32)[:, None, :, None]

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, seg, True, None, 32, 32, True)
        return jnp.sum((out * w) ** 2)

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True, segments=seg)
        return jnp.sum((out * w) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_flash_segments_equal_separate_sequences(nprng):
    """Two sequences packed into one row must attend exactly as when each
    runs alone — the no-cross-talk property the packing contract promises."""
    H, D, T = 2, 8, 64
    a_len, b_len = 24, 40
    q, k, v = (_rand(nprng, (1, H, T, D)) for _ in range(3))
    seg = jnp.asarray(
        np.concatenate([np.full(a_len, 1), np.full(b_len, 2)])[None], jnp.int32)
    packed = flash_attention(q, k, v, seg, True, None, 32, 32, True)
    alone_a = reference_attention(q[:, :, :a_len], k[:, :, :a_len],
                                  v[:, :, :a_len], causal=True)
    alone_b = reference_attention(q[:, :, a_len:], k[:, :, a_len:],
                                  v[:, :, a_len:], causal=True)
    np.testing.assert_allclose(np.asarray(packed[:, :, :a_len]),
                               np.asarray(alone_a), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(packed[:, :, a_len:]),
                               np.asarray(alone_b), rtol=2e-5, atol=2e-5)


# -------------------------------------------------------------- ring/ulysses

def _dense_oracle_bthd(q, k, v, seg, causal):
    """[B, T, H, D]-layout oracle with segment mask."""
    out = reference_attention(jnp.moveaxis(q, 2, 1), jnp.moveaxis(k, 2, 1),
                              jnp.moveaxis(v, 2, 1), causal=causal,
                              segments=seg)
    return jnp.moveaxis(out, 1, 2)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_segments_match_oracle(nprng, causal):
    mesh = pt.make_mesh({"seq": 8})
    B, T, H, D = 2, 32, 2, 4
    q, k, v = (_rand(nprng, (B, T, H, D)) for _ in range(3))
    seg = _packed_segments(nprng, B, T)
    ring = parallel.make_ring_attention(mesh, seq_axis="seq", causal=causal,
                                        with_segments=True)
    out = jax.jit(ring)(q, k, v, seg)
    ref = _dense_oracle_bthd(q, k, v, seg, causal)
    mask = _valid_rows(seg)[:, :, None, None]
    np.testing.assert_allclose(np.asarray(out) * mask, np.asarray(ref) * mask,
                               rtol=2e-5, atol=2e-5)


def test_ring_segments_grads_match_oracle(nprng):
    mesh = pt.make_mesh({"seq": 8})
    B, T, H, D = 1, 32, 1, 4
    q, k, v = (_rand(nprng, (B, T, H, D)) for _ in range(3))
    seg = _packed_segments(nprng, B, T)
    w = jnp.asarray(_valid_rows(seg), jnp.float32)[:, :, None, None]
    ring = parallel.make_ring_attention(mesh, seq_axis="seq", causal=True,
                                        with_segments=True)

    def loss_ring(q, k, v):
        return jnp.sum((ring(q, k, v, seg) * w) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum((_dense_oracle_bthd(q, k, v, seg, True) * w) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_segments_match_oracle(nprng, causal):
    mesh = pt.make_mesh({"seq": 4}, devices=jax.devices()[:4])
    B, T, H, D = 2, 32, 4, 4
    q, k, v = (_rand(nprng, (B, T, H, D)) for _ in range(3))
    seg = _packed_segments(nprng, B, T)
    uly = parallel.make_ulysses_attention(mesh, seq_axis="seq", causal=causal,
                                          with_segments=True)
    out = jax.jit(uly)(q, k, v, seg)
    ref = _dense_oracle_bthd(q, k, v, seg, causal)
    mask = _valid_rows(seg)[:, :, None, None]
    np.testing.assert_allclose(np.asarray(out) * mask, np.asarray(ref) * mask,
                               rtol=2e-5, atol=2e-5)


# --------------------------------------------------- model-level integration

def test_mha_impls_agree_on_packed_batch(nprng):
    """MultiHeadAttention must produce identical outputs for a packed batch
    on the XLA, flash, ring and ulysses paths (same params)."""
    from paddle_tpu.nn.attention import MultiHeadAttention
    B, T, D, Hh = 2, 32, 16, 4
    x = _rand(nprng, (B, T, D))
    seg = _packed_segments(nprng, B, T)
    mesh = pt.make_mesh({"seq": 4}, devices=jax.devices()[:4])
    outs = {}
    params = None
    for impl in ("xla", "flash", "ring", "seq"):
        mha = MultiHeadAttention(Hh, attention_impl=impl,
                                 seq_mesh=mesh if impl in ("ring", "seq")
                                 else None)
        if params is None:
            params = mha.init(jax.random.PRNGKey(0), x, causal=True,
                              segments=seg)
        outs[impl] = mha.apply(params, x, causal=True, segments=seg)
    mask = _valid_rows(seg)[:, :, None]
    base = np.asarray(outs["xla"]) * mask
    for impl in ("flash", "ring", "seq"):
        np.testing.assert_allclose(np.asarray(outs[impl]) * mask, base,
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"impl={impl}")


def test_transformer_lm_trains_on_packed_batch(nprng):
    """A packed variable-length batch trains through the flash path and
    matches the XLA path's loss/grads — the seam VERDICT r2 called out."""
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    vocab, T, B = 50, 32, 2
    seqs = [nprng.randint(1, vocab, size=nprng.randint(4, 14))
            for _ in range(6)]
    data, seg, pos = pack_sequences(seqs, row_len=T)
    data, seg, pos = data[:B], jnp.asarray(seg[:B]), jnp.asarray(pos[:B])
    ids = jnp.asarray(data)

    losses = {}
    grads = {}
    params = None
    for impl in ("xla", "flash"):
        model = TransformerLM(vocab=vocab, dim=32, num_layers=2, num_heads=2,
                              ffn_hidden=64, max_len=T, attention_impl=impl)
        if params is None:
            params = model.init(jax.random.PRNGKey(0), ids, segments=seg,
                                positions=pos)

        def loss_fn(p):
            logits = model.apply(p, ids, segments=seg, positions=pos)
            per_tok = costs.softmax_cross_entropy(
                logits.reshape(-1, vocab), ids.reshape(-1))
            w = (np.asarray(seg) > 0).astype(np.float32).reshape(-1)
            return jnp.sum(per_tok * w) / w.sum()

        losses[impl], grads[impl] = jax.value_and_grad(loss_fn)(params)
    np.testing.assert_allclose(float(losses["flash"]), float(losses["xla"]),
                               rtol=1e-4)
    flat_x = jax.tree_util.tree_leaves(grads["xla"])
    flat_f = jax.tree_util.tree_leaves(grads["flash"])
    for a, b in zip(flat_x, flat_f):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-3, atol=2e-5)
