"""Telemetry subsystem tests (ISSUE 2): JSONL sink schema round-trip,
retrace counter keyed by step fingerprint, health monitors flagging an
injected NaN, and the telemetry-off zero-overhead invariant (no extra
dispatches, no fences, no health outputs, bit-identical params).

ISSUE 4 satellites ride here too: thread-safe sink emit, the final
`summary` record at Telemetry.close(), and the PEAK_FLOPS v6e entry +
one-shot unknown-TPU-kind log (the tracer/anomaly layer itself is
tests/test_trace.py, including the tracing-off zero-overhead pin)."""

import json
import logging
import os

import numpy as np
import jax
import pytest

from paddle_tpu import optim
from paddle_tpu.models import MnistMLP
from paddle_tpu.nn import costs
from paddle_tpu.train import Trainer, events as ev
from paddle_tpu.obs import (HEALTH_KEYS, InMemorySink, JsonlSink,
                            LoggingSink, Telemetry)
from paddle_tpu.utils.stats import StatSet

BS, DIM = 16, 12


def make_batches(n, bs=BS, dim=DIM, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.normal(size=(bs, dim)).astype(np.float32),
             "label": rng.randint(0, 4, size=bs).astype(np.int32)}
            for _ in range(n)]


def make_trainer(K=2, M=2, telemetry=None):
    return Trainer(
        model=MnistMLP(num_classes=4, hidden=(8,)),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3),
        steps_per_call=K, grad_accum=M, telemetry=telemetry)


def run_fused(trainer, batches, log_period=0):
    trainer.init(jax.random.PRNGKey(0), batches[0])
    trainer.train(lambda: iter(batches), num_passes=1,
                  log_period=log_period)
    return trainer


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------

def test_jsonl_sink_schema_roundtrip(tmp_path):
    """Records written through JsonlSink parse back identical to what the
    in-memory sink saw — the schema survives the serialization."""
    path = str(tmp_path / "tel.jsonl")
    mem = InMemorySink()
    tel = Telemetry(sinks=[mem, JsonlSink(path)])
    batches = make_batches(2 * 2 * 2 + 1)     # +1 ragged tail
    run_fused(make_trainer(telemetry=tel), batches)
    tel.close()
    from_disk = JsonlSink.read(path)
    assert from_disk == mem.records
    steps = [r for r in from_disk if r["kind"] == "step"]
    compiles = [r for r in from_disk if r["kind"] == "compile"]
    assert steps and compiles
    for r in steps:
        for key in ("ts", "pass", "step", "k_steps", "m", "loss",
                    "host_stack_ms", "shard_ms", "dispatch_ms", "device_ms",
                    "replay_ms", "compile_count", "retrace_count",
                    "peak_bytes", "fenced") + HEALTH_KEYS:
            assert key in r, f"missing {key}"
        assert r["fenced"] is True and r["device_ms"] is not None
    for r in compiles:
        assert r["wall_s"] > 0
        assert "hlo_flops" in r


def test_sink_emit_thread_safe(tmp_path):
    """ISSUE 4 satellite: tracer spans finish on the stager thread, so
    sinks are written from two threads — concurrent emits must all land
    (InMemorySink) and JSONL lines must never interleave (JsonlSink)."""
    import threading
    path = str(tmp_path / "conc.jsonl")
    mem, jsonl = InMemorySink(), JsonlSink(path)
    n_threads, per_thread = 8, 200

    def worker(tid):
        for i in range(per_thread):
            rec = {"kind": "step", "tid": tid, "i": i,
                   "pad": "x" * 200}            # long enough to tear
            mem.emit(rec)
            jsonl.emit(rec)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    jsonl.close()
    assert len(mem.records) == n_threads * per_thread
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert len(lines) == n_threads * per_thread   # every line parses whole
    for tid in range(n_threads):
        assert [r["i"] for r in lines if r["tid"] == tid] == \
            list(range(per_thread))


def test_jsonl_sink_rotation_bounds_the_file(tmp_path):
    """ISSUE 6 satellite: JsonlSink(max_bytes=) rotates to <path>.1 and
    keeps writing — no record lost, no record split, both files bounded,
    and the telemetry JSONL of a long run stops growing unboundedly."""
    path = str(tmp_path / "tel.jsonl")
    rec = {"kind": "step", "i": 0, "pad": "x" * 80}
    line_len = len(json.dumps(rec)) + 1
    sink = JsonlSink(path, max_bytes=4 * line_len)
    n = 11
    for i in range(n):
        sink.emit({**rec, "i": i})
    sink.close()
    assert sink.rotations >= 1
    assert os.path.exists(path + ".1")
    main = JsonlSink.read(path)
    rotated = JsonlSink.read(path + ".1")
    # the retained window is the most recent records, contiguous across
    # .1 -> live with no record split, duplicated, or reordered (older
    # rotations are dropped by design — that IS the disk bound)
    window = [r["i"] for r in rotated + main]
    assert window == list(range(n - len(window), n))
    assert len(rotated) >= 1 and main[-1]["i"] == n - 1
    assert os.path.getsize(path) <= 4 * line_len
    assert os.path.getsize(path + ".1") <= 4 * line_len
    # a second sink on the same path resumes the byte count (append mode)
    sink2 = JsonlSink(path, max_bytes=4 * line_len)
    for i in range(n, n + 6):
        sink2.emit({**rec, "i": i})
    sink2.close()
    assert os.path.getsize(path) <= 4 * line_len
    assert JsonlSink.read(path)[-1]["i"] == n + 5


def test_jsonl_sink_oversized_record_still_lands(tmp_path):
    path = str(tmp_path / "big.jsonl")
    sink = JsonlSink(path, max_bytes=16)
    sink.emit({"kind": "step", "pad": "y" * 100})   # one line > max_bytes
    sink.close()
    assert len(JsonlSink.read(path)) == 1


def test_report_cli_summarizes_run(tmp_path):
    """ISSUE 6 satellite: `python -m paddle_tpu.obs.report run.jsonl`
    prints throughput / MFU / retraces / overlap / anomalies, preferring
    the final summary record, and --json round-trips."""
    from paddle_tpu.obs import report as report_cli
    path = str(tmp_path / "run.jsonl")
    tel = Telemetry(sinks=[JsonlSink(path)], tokens_per_step=128,
                    flops_per_step=1e9, peak_flops=1e12)
    run_fused(make_trainer(telemetry=tel), make_batches(2 * 2 * 2))
    # anomaly + attribution records ride the same stream
    tel.emit_event({"kind": "anomaly", "anomaly_kind": "slow_step",
                    "step": 3, "detail": "test"})
    tel.close()
    records = report_cli.load_records(path)
    s = report_cli.summarize(records)
    assert s["from_summary_record"] is True
    assert s["steps"] > 0 and s["optimizer_steps"] >= s["steps"]
    assert s["compiles"] >= 1
    assert s["anomalies"] == 1 and s["anomaly_kinds"] == ["slow_step"]
    assert s["est_mfu_pct"] is not None
    assert s["mean_dispatch_ms"] is not None
    table = report_cli.format_summary(s)
    assert "est MFU" in table and "anomalies" in table
    # CLI entry: table and --json modes both exit 0
    assert report_cli.main([path]) == 0
    assert report_cli.main([path, "--json"]) == 0
    assert report_cli.main([str(tmp_path / "missing.jsonl")]) == 2


def test_report_cli_without_summary_record(tmp_path):
    """A crashed run (no close, no summary record) still reports from
    the step records."""
    from paddle_tpu.obs import report as report_cli
    path = str(tmp_path / "crash.jsonl")
    tel = Telemetry(sinks=[JsonlSink(path)])
    run_fused(make_trainer(telemetry=tel), make_batches(2 * 2 * 2))
    for s in tel.sinks:                        # flush without summary
        s.close()
    s = report_cli.summarize(report_cli.load_records(path))
    assert s["from_summary_record"] is False
    assert s["steps"] > 0 and s["last_loss"] is not None


def test_anomaly_verdicts_echoed_into_telemetry_stream(tmp_path):
    """The Trainer echoes each detector verdict as a kind="anomaly"
    record so the JSONL is self-contained (the report CLI counts them
    without reading bundle directories)."""
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.nn import costs as nn_costs
    from paddle_tpu.obs import AnomalyDetector
    from paddle_tpu import optim as optim_lib
    mem = InMemorySink()
    tr = Trainer(
        model=MnistMLP(num_classes=4, hidden=(8,)),
        loss_fn=lambda out, b: nn_costs.softmax_cross_entropy(
            out, b["label"]),
        optimizer=optim_lib.adam(1e-3), steps_per_call=2, grad_accum=1,
        telemetry=Telemetry(sinks=[mem]),
        anomaly=AnomalyDetector(out_dir=str(tmp_path)))
    batches = make_batches(4)
    batches[2]["x"][0, 0] = np.nan
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    anomalies = mem.by_kind("anomaly")
    assert len(anomalies) == 1
    assert anomalies[0]["anomaly_kind"] == "nonfinite"
    assert anomalies[0]["bundle"]


def test_telemetry_close_emits_summary_record(tmp_path):
    """ISSUE 4 satellite: close() writes one final `summary` record so the
    JSONL is self-contained; a second close neither re-emits nor fails."""
    path = str(tmp_path / "tel.jsonl")
    mem = InMemorySink()
    tel = Telemetry(sinks=[mem, JsonlSink(path)])
    run_fused(make_trainer(telemetry=tel), make_batches(2 * 2 * 2))
    tel.close()
    tel.close()                                   # idempotent
    on_disk = JsonlSink.read(path)
    summaries = [r for r in on_disk if r["kind"] == "summary"]
    assert len(summaries) == 1 and on_disk[-1]["kind"] == "summary"
    s = summaries[0]
    assert s["steps_emitted"] == 2 and s["compile_count"] >= 1
    assert s["stager_leaked"] is False
    assert "mean_dispatch_ms" in s                # the aggregate view
    assert mem.by_kind("summary") == summaries    # every sink got it


def test_profiled_records_excluded_from_rates_and_means():
    """A profiled call (anomaly-armed jax.profiler capture) fences inside
    its dispatch window — emit_step must not derive a rate from it and
    summary() must not average its breakdown."""
    mem = InMemorySink()
    tel = Telemetry(sinks=[mem], tokens_per_step=100, peak_flops=1e12,
                    flops_per_step=1e9)
    tel.emit_step({"k_steps": 1, "dispatch_ms": 1.0, "device_ms": 1.0})
    tel.emit_step({"k_steps": 1, "dispatch_ms": 1.0, "device_ms": 1.0})
    rec = tel.emit_step({"k_steps": 1, "dispatch_ms": 5000.0,
                         "profiled": True})
    assert rec.get("tokens_per_sec") is None     # no rate from a fenced
    assert rec.get("est_mfu_pct") is None        # dispatch window
    s = tel.summary()
    assert s["mean_dispatch_ms"] == 1.0          # profiled not averaged
    # unprofiled records carry profiled=False in the fixed schema
    assert mem.by_kind("step")[0]["profiled"] is False


def test_peak_flops_v6e_and_unknown_kind_one_shot_log(caplog):
    """ISSUE 4 satellite: TPU v6e is in the MFU table, and an unknown TPU
    kind logs a one-shot WARNING instead of silently returning None."""
    from paddle_tpu.obs import PEAK_FLOPS, device_peak_flops
    from paddle_tpu.obs import telemetry as tel_mod
    assert PEAK_FLOPS["TPU v6 lite"] == PEAK_FLOPS["TPU v6e"] == 918e12

    class FakeDev:
        device_kind = "TPU v99 hyper"

    tel_mod._unknown_kinds_logged.discard("TPU v99 hyper")
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.telemetry"):
        assert device_peak_flops(FakeDev()) is None
        assert device_peak_flops(FakeDev()) is None   # second call silent
    hits = [r for r in caplog.records if "TPU v99 hyper" in r.getMessage()]
    assert len(hits) == 1
    assert "PEAK_FLOPS" in hits[0].getMessage()

    class Known:
        device_kind = "TPU v6e"

    assert device_peak_flops(Known()) == 918e12


def test_logging_sink_emits(caplog):
    sink = LoggingSink(level=logging.INFO)
    with caplog.at_level(logging.INFO, logger="paddle_tpu.telemetry"):
        sink.emit({"kind": "step", "step": 3, "dispatch_ms": 1.25,
                   "grad_norm": 0.5})
        sink.emit({"kind": "compile", "compile_count": 1, "wall_s": 0.1,
                   "hlo_flops": 100.0, "fingerprint": "fp"})
    text = caplog.text
    assert "step=3" in text and "compile" in text


def test_broken_sink_never_kills_training():
    class Boom:
        def emit(self, record):
            raise RuntimeError("sink died")

    tel = Telemetry(sinks=[Boom(), InMemorySink()])
    batches = make_batches(2 * 2 * 2)
    run_fused(make_trainer(telemetry=tel), batches)   # must not raise
    assert tel.compile_count >= 1


# ---------------------------------------------------------------------------
# retrace / compile tracking
# ---------------------------------------------------------------------------

def test_retrace_counter_increments_once_per_fingerprint():
    tel = Telemetry(sinks=[InMemorySink()])
    assert tel.observe_fingerprint(("a",)) is True
    assert tel.observe_fingerprint(("a",)) is False
    assert tel.observe_fingerprint(("a",)) is False
    assert (tel.compile_count, tel.retrace_count) == (1, 0)
    assert tel.observe_fingerprint(("b",)) is True
    assert tel.observe_fingerprint(("b",)) is False
    assert tel.observe_fingerprint(("a",)) is False
    assert (tel.compile_count, tel.retrace_count) == (2, 1)


def test_trainer_retrace_tracking_ragged_tail():
    """K*M-uniform groups compile once; the ragged pass tail is a second
    fingerprint (ONE retrace), and a second pass over the same stream adds
    none — the counter keys on fingerprints, not dispatches."""
    mem = InMemorySink()
    tel = Telemetry(sinks=[mem])
    tr = make_trainer(K=2, M=2, telemetry=tel)
    batches = make_batches(2 * 2 * 2 + 1)      # two full groups + tail 1
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert tel.compile_count == 2              # full-group + tail shapes
    assert tel.retrace_count == 1
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert tel.compile_count == 2              # nothing new the 2nd pass
    assert tel.retrace_count == 1
    assert len(mem.by_kind("compile")) == 2
    # compile records carry wall time and the HLO FLOPs estimate
    for r in mem.by_kind("compile"):
        assert r["wall_s"] > 0


def test_retrace_warning_one_shot(caplog):
    """ISSUE 3 satellite: crossing the distinct-fingerprint threshold logs
    ONE warning pointing at drop_last/padding — and only once."""
    tel = Telemetry(sinks=[InMemorySink()], retrace_warn_threshold=2)
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.telemetry"):
        tel.observe_fingerprint(("a",))       # initial compile
        tel.observe_fingerprint(("b",))       # retrace 1: below threshold
        assert "drop_last" not in caplog.text
        tel.observe_fingerprint(("c",))       # retrace 2: fires
        tel.observe_fingerprint(("d",))       # retrace 3: already warned
    warnings = [r for r in caplog.records
                if "drop_last" in r.getMessage()]
    assert len(warnings) == 1
    assert "recompile" in warnings[0].getMessage()


def test_mfu_and_tokens_per_sec_accounting():
    """With an explicit peak-FLOPs denominator (the CPU table has none)
    emit_step derives est_mfu_pct from the analytic flops_per_step."""
    mem = InMemorySink()
    tel = Telemetry(sinks=[mem], flops_per_step=1e9, tokens_per_step=1024,
                    peak_flops=1e12)
    tel.emit_step({"k_steps": 2, "dispatch_ms": 1.0, "device_ms": 9.0})
    rec = mem.records[-1]
    # per-step time = 10ms/2 = 5ms -> 1e9 / 5e-3 / 1e12 = 20% MFU
    assert rec["est_mfu_pct"] == pytest.approx(20.0)
    assert rec["tokens_per_sec"] == pytest.approx(1024 / 5e-3)


# ---------------------------------------------------------------------------
# health monitors
# ---------------------------------------------------------------------------

def test_health_monitors_flag_injected_nan(tmp_path):
    path = str(tmp_path / "nan.jsonl")
    mem = InMemorySink()
    tel = Telemetry(sinks=[mem, JsonlSink(path)])
    tr = make_trainer(K=2, M=1, telemetry=tel)
    batches = make_batches(4)
    batches[2]["x"][0, 0] = np.nan            # poison one microbatch
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    tel.close()
    steps = mem.by_kind("step")
    assert len(steps) == 2                    # 4 batches / K=2 per call
    assert steps[0]["nonfinite_count"] == 0
    assert steps[0]["grad_norm"] > 0
    # the poisoned call: the sentinel trips; the NaN norms/loss are
    # sanitized to None so the JSONL stays strict-RFC-8259 parseable
    assert steps[1]["nonfinite_count"] > 0
    assert steps[1]["grad_norm"] is None
    assert steps[1]["loss"] is None

    def no_nan_literals(name):
        raise AssertionError(f"bare {name} literal in JSONL")

    with open(path) as f:
        for line in f:                        # strict parse: NaN/Inf reject
            json.loads(line, parse_constant=no_nan_literals)


def test_healthy_run_monitor_values():
    mem = InMemorySink()
    tel = Telemetry(sinks=[mem])
    run_fused(make_trainer(telemetry=tel), make_batches(8))
    for r in mem.by_kind("step"):
        assert r["nonfinite_count"] == 0
        assert r["grad_norm"] > 0
        assert r["param_norm"] > 0
        assert 0 < r["update_ratio"] < 1


# ---------------------------------------------------------------------------
# the telemetry-off zero-overhead invariant
# ---------------------------------------------------------------------------

def _count_dispatches(tr, batches, monkeypatch_fence=None):
    """Run one pass counting fused-step dispatches (and optionally
    block_until_ready fences)."""
    tr.init(jax.random.PRNGKey(0), batches[0])
    calls = {"n": 0}
    orig_dispatch = tr._dispatch_fused

    def counting_dispatch(stacked, rng, **kw):
        calls["n"] += 1
        return orig_dispatch(stacked, rng, **kw)

    tr._dispatch_fused = counting_dispatch
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    return calls["n"]


def test_telemetry_off_zero_dispatch_and_fence_overhead(monkeypatch):
    """With telemetry off the fused loop adds NOTHING: same dispatch count
    as the telemetered run, zero block_until_ready fences, no health
    outputs in the traced step, and bit-identical trained params."""
    batches = make_batches(2 * 2 * 3)
    fences = {"n": 0}
    orig_fence = jax.block_until_ready

    def counting_fence(x):
        fences["n"] += 1
        return orig_fence(x)

    monkeypatch.setattr(jax, "block_until_ready", counting_fence)

    tr_off = make_trainer(telemetry=None)
    n_off = _count_dispatches(tr_off, batches)
    fences_off = fences["n"]
    assert fences_off == 0                    # telemetry owns the fence
    # no health outputs traced into the step: 6-tuple contract
    out = tr_off._fused_step
    assert out is not None
    assert not tr_off._health_on()

    tel = Telemetry(sinks=[InMemorySink()])
    tr_on = make_trainer(telemetry=tel)
    n_on = _count_dispatches(tr_on, batches)
    assert n_on == n_off                      # telemetry adds no dispatch
    assert fences["n"] > 0                    # ...but does fence when on
    assert tr_on._health_on()

    # telemetry (health outputs included) must not perturb the math
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(
                tr_off.train_state.params)),
            jax.tree_util.tree_leaves(jax.device_get(
                tr_on.train_state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_telemetry_event_fires_only_when_attached():
    batches = make_batches(2 * 2 * 2)
    seen = {"on": 0, "off": 0}

    tr = make_trainer(telemetry=None)
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0,
             event_handler=lambda e: seen.__setitem__(
                 "off", seen["off"] + isinstance(e, ev.TelemetryRecord)))
    assert seen["off"] == 0

    tr = make_trainer(telemetry=Telemetry(sinks=[InMemorySink()]))
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0,
             event_handler=lambda e: seen.__setitem__(
                 "on", seen["on"] + isinstance(e, ev.TelemetryRecord)))
    assert seen["on"] == 2                    # one per fused call


def test_plain_loop_telemetry_records():
    """steps_per_call=1, grad_accum=1 (the unfused loop) also records a
    per-step breakdown and retraces."""
    mem = InMemorySink()
    tr = make_trainer(K=1, M=1, telemetry=Telemetry(sinks=[mem]))
    batches = make_batches(3)
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    steps = mem.by_kind("step")
    assert len(steps) == 3
    for r in steps:
        assert r["k_steps"] == 1
        assert r["shard_ms"] is not None and r["dispatch_ms"] is not None
        assert r["device_ms"] is not None and r["fenced"] is True
        assert r["grad_norm"] > 0
    assert len(mem.by_kind("compile")) == 1


# ---------------------------------------------------------------------------
# StatSet satellite
# ---------------------------------------------------------------------------

def test_statset_report_topn_and_to_dict():
    s = StatSet("t")
    s.add("slow", 2.0)
    s.add("fast", 0.1)
    s.add("mid", 0.5)
    rep = s.report(top_n=2)
    lines = rep.splitlines()
    assert "slow" in lines[1]                 # sorted by total desc
    assert "mid" in lines[2]
    assert "fast" not in rep
    assert "1 more" in lines[-1]
    d = s.to_dict()
    assert d["name"] == "t"
    assert d["stats"]["slow"]["count"] == 1
    json.dumps(d)                             # JSON-ready
    s.reset()
    assert s.summary() == {}


# ---------------------------------------------------------------------------
# named_scope satellite: profiler traces show model structure
# ---------------------------------------------------------------------------

def test_transformer_named_scopes_reach_compiled_hlo():
    from paddle_tpu.models import TransformerLM
    import jax.numpy as jnp

    model = TransformerLM(vocab=32, dim=16, num_layers=2, num_heads=2,
                          ffn_hidden=32, max_len=8)
    ids = jnp.zeros((2, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    compiled = jax.jit(
        lambda p, i: model.apply(p, i)).lower(variables, ids).compile()
    txt = compiled.as_text()
    for scope in ("embed", "block0", "block1", "attn", "ffn", "head",
                  "qkv_proj", "sdpa_xla", "out_proj"):
        assert scope in txt, f"named_scope {scope!r} missing from HLO"
