"""Detection family tests — numeric oracles per layer + SSD skeleton
(the analog of the reference's ``test_LayerGrad`` detection cases and
``test_Evaluator.cpp`` detection_map coverage)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nn.detection import (DetectionOutput, MultiBoxLoss, ROIPool,
                                     decode_boxes, encode_boxes, iou_matrix,
                                     match_priors, nms, prior_box)


# ------------------------------------------------------------------ priors

def test_prior_box_count_and_geometry():
    # 2x2 feature map on a 100x100 image: 1 min_size + max_size + ar 2 (+flip)
    boxes, var = prior_box((2, 2), (100, 100), min_sizes=[30],
                           max_sizes=[60], aspect_ratios=[2.0])
    # per cell: min, sqrt(min*max), ar=2, ar=0.5  -> 4 priors
    assert boxes.shape == (2 * 2 * 4, 4)
    assert var.shape == boxes.shape
    b = np.asarray(boxes)
    # first cell center is (25, 25); first box is the 30x30 min box
    np.testing.assert_allclose(b[0], [0.10, 0.10, 0.40, 0.40], atol=1e-6)
    # second is sqrt(30*60) ~ 42.43 square
    s = np.sqrt(30 * 60) / 100
    np.testing.assert_allclose(b[1], [0.25 - s / 2, 0.25 - s / 2,
                                      0.25 + s / 2, 0.25 + s / 2], atol=1e-6)
    # all clipped into [0, 1]
    assert (b >= 0).all() and (b <= 1).all()
    # widths/heights of ar-2 box: w = 30*sqrt(2), h = 30/sqrt(2) (unclipped
    # cells in the middle would show it; check cell (1,1) = boxes 12..15)
    w = (b[14, 2] - b[14, 0]) * 100
    h = (b[14, 3] - b[14, 1]) * 100
    np.testing.assert_allclose([w, h], [30 * np.sqrt(2), 30 / np.sqrt(2)],
                               atol=1e-4)


def test_encode_decode_roundtrip():
    rng = np.random.RandomState(0)
    # sorting two corner points elementwise yields valid (xmin,ymin,xmax,ymax)
    priors = np.sort(rng.uniform(0, 1, (20, 2, 2)), axis=1).reshape(20, 4)
    var = np.tile([0.1, 0.1, 0.2, 0.2], (20, 1)).astype(np.float32)
    gt = np.sort(rng.uniform(0.05, 0.95, (20, 2, 2)), axis=1).reshape(20, 4)
    enc = encode_boxes(jnp.asarray(priors, jnp.float32),
                       jnp.asarray(var), jnp.asarray(gt, jnp.float32))
    dec = decode_boxes(jnp.asarray(priors, jnp.float32), jnp.asarray(var), enc)
    np.testing.assert_allclose(np.asarray(dec), gt, atol=1e-4)


# ----------------------------------------------------------------- matching

def _match_oracle(priors, gts, threshold):
    """Scalar-loop transcription of the reference's matchBBox semantics."""
    P, G = len(priors), len(gts)
    ov = np.array(iou_matrix(jnp.asarray(priors), jnp.asarray(gts)))
    ov[ov <= 1e-6] = 0.0
    match = np.full(P, -1)
    best_overlap = ov.max(axis=1) if G else np.zeros(P)
    avail = ov.copy()
    for _ in range(G):
        i, j = np.unravel_index(np.argmax(avail), avail.shape)
        if avail[i, j] <= 0:
            break
        match[i] = j
        avail[i, :] = -1
        avail[:, j] = -1
    for i in range(P):
        if match[i] < 0 and best_overlap[i] >= threshold:
            match[i] = np.argmax(ov[i])
    return match, best_overlap


@pytest.mark.parametrize("seed", range(3))
def test_match_priors_vs_oracle(seed):
    rng = np.random.RandomState(seed)
    P, G = 12, 4
    pts = rng.uniform(0, 0.8, (P, 2)).astype(np.float32)
    priors = np.concatenate([pts, pts + rng.uniform(0.1, 0.2, (P, 2))], 1)
    gts = np.concatenate([(q := rng.uniform(0, 0.8, (G, 2)).astype(np.float32)),
                          q + rng.uniform(0.1, 0.2, (G, 2))], 1)
    got_m, got_o = match_priors(jnp.asarray(priors), jnp.asarray(gts),
                                jnp.ones(G, bool), 0.3)
    want_m, want_o = _match_oracle(priors, gts, 0.3)
    np.testing.assert_array_equal(np.asarray(got_m), want_m)
    np.testing.assert_allclose(np.asarray(got_o), want_o, atol=1e-6)


def test_match_respects_gt_padding():
    priors = jnp.asarray([[0.0, 0.0, 0.5, 0.5]], jnp.float32)
    gts = jnp.asarray([[0.0, 0.0, 0.5, 0.5], [0.0, 0.0, 0.5, 0.5]],
                      jnp.float32)
    m, _ = match_priors(priors, gts, jnp.asarray([False, True]), 0.5)
    assert int(m[0]) == 1        # padded gt 0 is invisible


# ---------------------------------------------------------------- multibox

def test_multibox_loss_finite_and_differentiable():
    rng = np.random.RandomState(0)
    priors, var = prior_box((3, 3), (90, 90), min_sizes=[30],
                            aspect_ratios=[2.0])
    P = priors.shape[0]
    C, B, G = 4, 2, 3
    loss_mod = MultiBoxLoss(priors, var, num_classes=C)
    params = loss_mod.init(jax.random.PRNGKey(0),
                           jnp.zeros((B, P, 4)), jnp.zeros((B, P, C)),
                           jnp.zeros((B, G, 4)),
                           -jnp.ones((B, G), jnp.int32))

    gt_boxes = np.zeros((B, G, 4), np.float32)
    gt_boxes[:, 0] = [0.1, 0.1, 0.4, 0.4]
    gt_labels = np.full((B, G), -1, np.int32)
    gt_labels[:, 0] = 1

    def loss_fn(loc, conf):
        return loss_mod.apply(params, loc, conf, jnp.asarray(gt_boxes),
                              jnp.asarray(gt_labels))

    loc = jnp.asarray(rng.normal(0, 0.1, (B, P, 4)).astype(np.float32))
    conf = jnp.asarray(rng.normal(0, 0.1, (B, P, C)).astype(np.float32))
    val, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(loc, conf)
    assert np.isfinite(float(val)) and float(val) > 0
    assert all(np.isfinite(np.asarray(g)).all() for g in grads)
    # perfect predictions should give a lower loss than noise
    valid = gt_labels[0] >= 0
    m, _ = match_priors(priors, jnp.asarray(gt_boxes[0]),
                        jnp.asarray(valid), 0.5)
    enc = encode_boxes(priors, var,
                       jnp.asarray(gt_boxes[0])[jnp.maximum(m, 0)])
    loc_perfect = jnp.where((m >= 0)[:, None], enc, 0.0)[None].repeat(B, 0)
    tgt = np.where(np.asarray(m) >= 0, gt_labels[0][np.maximum(m, 0)], 0)
    conf_perfect = jnp.asarray(
        20.0 * np.eye(C, dtype=np.float32)[tgt])[None].repeat(B, 0)
    assert float(loss_fn(loc_perfect, conf_perfect)) < float(val)


def test_multibox_no_gt_gives_zero_positive_loss():
    priors, var = prior_box((2, 2), (60, 60), min_sizes=[20])
    P = priors.shape[0]
    mod = MultiBoxLoss(priors, var, num_classes=3)
    params = {}
    loss = mod.apply(params, jnp.zeros((1, P, 4)), jnp.zeros((1, P, 3)),
                     jnp.zeros((1, 2, 4)), -jnp.ones((1, 2), jnp.int32))
    # no positives -> no loc loss and no mined negatives -> loss 0
    assert float(loss) == 0.0


# --------------------------------------------------------------------- nms

def _nms_oracle(boxes, scores, iou_thr, score_thr):
    order = np.argsort(-scores, kind="stable")
    keep = []
    for i in order:
        if scores[i] <= score_thr:
            continue
        ok = True
        for j in keep:
            if float(iou_matrix(jnp.asarray(boxes[i][None]),
                                jnp.asarray(boxes[j][None]))[0, 0]) > iou_thr:
                ok = False
                break
        if ok:
            keep.append(i)
    return keep


@pytest.mark.parametrize("seed", range(3))
def test_nms_vs_oracle(seed):
    rng = np.random.RandomState(seed)
    N = 16
    pts = rng.uniform(0, 0.7, (N, 2)).astype(np.float32)
    boxes = np.concatenate([pts, pts + 0.25], 1)
    scores = rng.uniform(0, 1, N).astype(np.float32)
    idxs, keep = nms(jnp.asarray(boxes), jnp.asarray(scores), max_out=N,
                     iou_threshold=0.4, score_threshold=0.05)
    got = list(np.asarray(idxs)[np.asarray(keep)])
    want = _nms_oracle(boxes, scores, 0.4, 0.05)
    assert got == want


def test_detection_output_shapes_and_recovery():
    priors, var = prior_box((4, 4), (80, 80), min_sizes=[20],
                            aspect_ratios=[2.0])
    P = priors.shape[0]
    C = 3
    det = DetectionOutput(priors, var, num_classes=C, keep_top_k=8,
                          nms_top_k=16)
    # craft conf so prior 5 is confidently class 1 and prior 20 class 2
    conf = np.full((1, P, C), -8.0, np.float32)
    conf[:, :, 0] = 8.0                       # background everywhere
    conf[0, 5] = [-8, 8, -8]
    conf[0, 20] = [-8, -8, 8]
    loc = np.zeros((1, P, 4), np.float32)     # predict the priors themselves
    out = det.apply({}, jnp.asarray(loc), jnp.asarray(conf))
    assert out.shape == (1, 8, 6)
    o = np.asarray(out[0])
    kept = o[o[:, 0] >= 0]
    assert len(kept) == 2
    labels = sorted(kept[:, 0].astype(int).tolist())
    assert labels == [1, 2]
    row1 = kept[kept[:, 0] == 1][0]
    np.testing.assert_allclose(row1[2:], np.asarray(priors[5]), atol=1e-5)


# ----------------------------------------------------------------- roipool

def _roipool_oracle(fmap, roi, ph, pw, scale):
    H, W, C = fmap.shape
    x1, y1, x2, y2 = [int(round(v * scale)) for v in roi]
    rw = max(x2 - x1 + 1, 1)
    rh = max(y2 - y1 + 1, 1)
    out = np.zeros((ph, pw, C), fmap.dtype)
    for i in range(ph):
        for j in range(pw):
            hs = min(max(int(np.floor(i * rh / ph)) + y1, 0), H)
            he = min(max(int(np.ceil((i + 1) * rh / ph)) + y1, 0), H)
            ws = min(max(int(np.floor(j * rw / pw)) + x1, 0), W)
            we = min(max(int(np.ceil((j + 1) * rw / pw)) + x1, 0), W)
            if he <= hs or we <= ws:
                out[i, j] = 0
            else:
                out[i, j] = fmap[hs:he, ws:we].max(axis=(0, 1))
    return out


@pytest.mark.parametrize("seed", range(2))
def test_roi_pool_vs_oracle(seed):
    rng = np.random.RandomState(seed)
    fmap = rng.normal(size=(1, 8, 8, 3)).astype(np.float32)
    rois = np.array([[0, 0, 0, 28, 28],
                     [0, 8, 4, 24, 20]], np.float32)
    mod = ROIPool(pooled_height=3, pooled_width=3, spatial_scale=0.25)
    out = mod.apply({}, jnp.asarray(fmap), jnp.asarray(rois))
    assert out.shape == (2, 3, 3, 3)
    for r in range(2):
        want = _roipool_oracle(fmap[0], rois[r, 1:], 3, 3, 0.25)
        np.testing.assert_allclose(np.asarray(out[r]), want, atol=1e-6)


# ------------------------------------------------------------ detection_map

def test_detection_map_perfect_and_mixed():
    from paddle_tpu.train.evaluators import DetectionMAP
    ev = DetectionMAP(overlap_threshold=0.5, ap_type="11point")
    gt_box = np.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]])
    gt_label = np.array([[1, 2]])
    det = np.full((1, 4, 6), -1.0)
    det[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]     # perfect match class 1
    det[0, 1] = [2, 0.8, 0.5, 0.5, 0.9, 0.9]     # perfect match class 2
    ev.update({"det": det, "gt_box": gt_box, "gt_label": gt_label,
               "gt_difficult": np.zeros((1, 2))})
    assert abs(ev.result()["detection_map"] - 100.0) < 1e-6

    # one false positive with higher score than the true positive:
    # precision at the tp is 0.5, so 11-point AP for that class drops
    ev2 = DetectionMAP(overlap_threshold=0.5, ap_type="Integral")
    det2 = np.full((1, 4, 6), -1.0)
    det2[0, 0] = [1, 0.95, 0.6, 0.6, 0.8, 0.8]   # fp (wrong place)
    det2[0, 1] = [1, 0.90, 0.1, 0.1, 0.4, 0.4]   # tp
    ev2.update({"det": det2, "gt_box": gt_box[:, :1], "gt_label":
                gt_label[:, :1], "gt_difficult": np.zeros((1, 1))})
    assert abs(ev2.result()["detection_map"] - 50.0) < 1e-6


def test_detection_map_duplicate_detection_is_fp():
    from paddle_tpu.train.evaluators import DetectionMAP
    ev = DetectionMAP(ap_type="Integral")
    gt_box = np.array([[[0.1, 0.1, 0.4, 0.4]]])
    gt_label = np.array([[1]])
    det = np.full((1, 3, 6), -1.0)
    det[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]
    det[0, 1] = [1, 0.8, 0.1, 0.1, 0.4, 0.4]     # duplicate -> fp
    ev.update({"det": det, "gt_box": gt_box, "gt_label": gt_label,
               "gt_difficult": np.zeros((1, 1))})
    # AP: tp first (p=1, r=1), duplicate fp after -> integral AP = 1
    assert abs(ev.result()["detection_map"] - 100.0) < 1e-6


def test_detection_map_difficult_ignored():
    from paddle_tpu.train.evaluators import DetectionMAP
    ev = DetectionMAP(ap_type="Integral", evaluate_difficult=False)
    gt_box = np.array([[[0.1, 0.1, 0.4, 0.4]]])
    gt_label = np.array([[1]])
    det = np.full((1, 2, 6), -1.0)
    det[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]
    ev.update({"det": det, "gt_box": gt_box, "gt_label": gt_label,
               "gt_difficult": np.ones((1, 1))})
    # the only gt is difficult: not counted as positive, detection ignored
    assert ev.result()["detection_map"] == 0.0


# ---------------------------------------------------------------- SSD skel

def test_ssd_skeleton_forward():
    """SSD head wiring: backbone feature maps -> loc/conf heads -> multibox
    loss and decoded detections (reference: the SSD config the detection
    layers exist for — PriorBox + MultiBoxLoss + DetectionOutput chained)."""
    from paddle_tpu.models.ssd import SSDHead
    rng = jax.random.PRNGKey(0)
    head = SSDHead(num_classes=4, feature_shapes=[(4, 4), (2, 2)],
                   image_shape=(64, 64), min_sizes=[16, 32],
                   max_sizes=[32, 48], aspect_ratios=[2.0])
    feats = [jnp.ones((2, 4, 4, 8)), jnp.ones((2, 2, 2, 8))]
    params = head.init(rng, feats)
    loc, conf = head.apply(params, feats)
    P = head.priors.shape[0]
    assert loc.shape == (2, P, 4) and conf.shape == (2, P, 4)

    gt_boxes = jnp.asarray([[[0.1, 0.1, 0.5, 0.5]]] * 2)
    gt_labels = jnp.asarray([[1]] * 2, jnp.int32)
    loss = head.multibox_loss().apply({}, loc, conf, gt_boxes, gt_labels)
    assert np.isfinite(float(loss))
    out = head.detection_output(keep_top_k=8).apply({}, loc, conf)
    assert out.shape == (2, 8, 6)


def test_detection_module_ir_roundtrip():
    """Array-valued constructor args (priors) must survive the model IR
    (config round-trip), so detection models are exportable."""
    from paddle_tpu.core.config import (build_module, config_from_json,
                                        config_to_json, module_config)
    priors, var = prior_box((2, 2), (32, 32), [8], [16], [2.0])
    m = DetectionOutput(priors, var, num_classes=3, keep_top_k=4, nms_top_k=8)
    cfg = config_from_json(config_to_json(module_config(m)))
    m2 = build_module(cfg, trusted=False)
    loc = jnp.zeros((1, priors.shape[0], 4))
    conf = jnp.zeros((1, priors.shape[0], 3))
    np.testing.assert_allclose(np.asarray(m.apply({}, loc, conf)),
                               np.asarray(m2.apply({}, loc, conf)))


def test_detection_output_shape_fixed_when_few_candidates():
    priors, var = prior_box((2, 2), (32, 32), [8])
    P = priors.shape[0]
    det = DetectionOutput(priors, var, num_classes=2, nms_top_k=2,
                          keep_top_k=16)
    out = det.apply({}, jnp.zeros((1, P, 4)), jnp.zeros((1, P, 2)))
    assert out.shape == (1, 16, 6)     # documented keep_top_k, padded


def test_ssd_trains_on_voc_and_maps(tmp_path):
    """Acceptance slice for the detection family: SSD head on the voc2012
    synthetic set — multibox loss decreases and detection mAP on train data
    beats an untrained head (the e2e pattern of the reference's detection
    demos)."""
    from paddle_tpu import optim
    from paddle_tpu.data import datasets
    from paddle_tpu.models.ssd import SSDHead
    from paddle_tpu.nn.layers import Conv2D
    from paddle_tpu.core.module import Module
    from paddle_tpu.train.evaluators import DetectionMAP

    class TinySSD(Module):
        def __init__(self):
            super().__init__()
            self.c1 = Conv2D(16, kernel=3, stride=2, act="relu")   # 48
            self.c2 = Conv2D(32, kernel=3, stride=2, act="relu")   # 24
            self.c3 = Conv2D(32, kernel=3, stride=2, act="relu")   # 12
            self.head = SSDHead(num_classes=5, feature_shapes=[(12, 12)],
                                image_shape=(96, 96), min_sizes=[24],
                                max_sizes=[40], aspect_ratios=[1.5])

        def forward(self, x):
            f = self.c3(self.c2(self.c1(x)))
            return self.head([f])

    model = TinySSD()
    reader = datasets.voc2012("train", n=128)
    rows = list(reader())
    B = 16

    def batches():
        for i in range(0, len(rows), B):
            chunk = rows[i:i + B]
            yield (jnp.asarray(np.stack([r[0] for r in chunk])),
                   jnp.asarray(np.stack([r[1] for r in chunk])),
                   jnp.asarray(np.stack([r[2] for r in chunk])))

    imgs, gb, gl = next(batches())
    variables = model.init(jax.random.PRNGKey(0), imgs)
    mbl = model.head.multibox_loss()
    from paddle_tpu.optim.optimizers import adam
    optzr = adam(3e-3)
    opt_state = optzr.init(variables["params"])

    @jax.jit
    def step(p, opt_state, sno, imgs, gb, gl):
        def loss_fn(p):
            loc, conf = model.apply({"params": p}, imgs)
            return mbl.apply({}, loc, conf, gb, gl)
        loss, g = jax.value_and_grad(loss_fn)(p)
        p, opt_state = optzr.apply(g, opt_state, p, sno)
        return loss, p, opt_state

    det = model.head.detection_output(keep_top_k=8,
                                      confidence_threshold=0.3)

    def eval_map(p):
        ev = DetectionMAP(ap_type="Integral")
        for imgs, gb, gl in batches():
            loc, conf = model.apply({"params": p}, imgs)
            out = det.apply({}, loc, conf)
            ev.update({"det": np.asarray(out), "gt_box": np.asarray(gb),
                       "gt_label": np.asarray(gl),
                       "gt_difficult": np.zeros(np.asarray(gl).shape)})
        return ev.result()["detection_map"]

    map_before = eval_map(variables["params"])
    p = variables["params"]
    first = None
    sno = 0
    for epoch in range(12):
        for imgs, gb, gl in batches():
            loss, p, opt_state = step(p, opt_state, jnp.asarray(sno),
                                      imgs, gb, gl)
            sno += 1
            if first is None:
                first = float(loss)
    assert float(loss) < 0.7 * first, (first, float(loss))
    map_after = eval_map(p)
    assert map_after > map_before + 5.0, (map_before, map_after)
