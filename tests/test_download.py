"""Download+cache machinery (the common.py analog) — VERDICT r2 item 8.

No network in this environment, so the transfer path is exercised with
``file://`` URLs and fabricated archives; the env gate, cache hits, md5
verification/retry, atomicity, and the real-data loader paths are all
pinned.
"""

import gzip
import hashlib
import io
import os
import struct
import tarfile

import numpy as np
import pytest

from paddle_tpu.data import datasets
from paddle_tpu.data.download import (DownloadDisabled, download,
                                      downloads_enabled, md5file)


@pytest.fixture
def home(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_DATA", str(tmp_path))
    return tmp_path


def _src(tmp_path, content=b"hello dataset"):
    src = tmp_path / "src.bin"
    src.write_bytes(content)
    return src, hashlib.md5(content).hexdigest()


def test_download_gate_off_raises(home, tmp_path, monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_AUTO_DOWNLOAD", raising=False)
    assert not downloads_enabled()
    src, md5 = _src(tmp_path)
    with pytest.raises(DownloadDisabled, match="AUTO_DOWNLOAD"):
        download(src.as_uri(), "mod", md5)


def test_download_fetches_verifies_and_caches(home, tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTO_DOWNLOAD", "1")
    src, md5 = _src(tmp_path)
    out = download(src.as_uri(), "mod", md5)
    assert out == str(home / "mod" / "src.bin")
    assert md5file(out) == md5
    # cache hit: works again even with downloads disabled
    monkeypatch.delenv("PADDLE_TPU_AUTO_DOWNLOAD")
    assert download(src.as_uri(), "mod", md5) == out
    assert not os.path.exists(out + ".part")     # atomic: no leftovers


def test_download_md5_mismatch_raises(home, tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTO_DOWNLOAD", "1")
    src, _ = _src(tmp_path)
    with pytest.raises(IOError, match="md5"):
        download(src.as_uri(), "mod", "0" * 32)
    assert not os.path.exists(home / "mod" / "src.bin")


def _write_idx(home, split):
    d = home / "mnist"
    d.mkdir(parents=True, exist_ok=True)
    n = 4
    imgs = np.arange(n * 28 * 28, dtype=np.uint8).reshape(n, 28, 28)
    labs = np.arange(n, dtype=np.uint8)
    prefix = "train" if split == "train" else "t10k"
    with gzip.open(d / f"{prefix}-images-idx3-ubyte.gz", "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28) + imgs.tobytes())
    with gzip.open(d / f"{prefix}-labels-idx1-ubyte.gz", "wb") as f:
        f.write(struct.pack(">II", 2049, n) + labs.tobytes())


def test_mnist_prefers_cached_real_files(home):
    _write_idx(home, "train")
    r = datasets.mnist("train")
    assert r.is_synthetic is False
    assert r.num_samples == 4
    x, y = next(iter(r()))
    assert x.shape == (28, 28, 1) and y == 0


def test_mnist_synthetic_fallback_is_labelled(home, monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_AUTO_DOWNLOAD", raising=False)
    r = datasets.mnist("train", synthetic_n=8)
    assert r.is_synthetic is True


def test_cifar100_real_pickles_parsed(home):
    import pickle
    d = home / "cifar-100-python"
    d.mkdir(parents=True)
    rng = np.random.RandomState(0)
    for split, n in (("train", 6), ("test", 3)):
        payload = {
            b"data": rng.randint(0, 256, size=(n, 3072)).astype(np.uint8),
            b"fine_labels": list(range(n)),
            b"coarse_labels": [i % 20 for i in range(n)],
        }
        (d / split).write_bytes(pickle.dumps(payload))

    r = datasets.cifar100("train")
    assert r.is_synthetic is False and r.num_samples == 6
    x, y = next(iter(r()))
    assert x.shape == (32, 32, 3) and x.dtype == np.float32
    assert x.min() >= -1.0 and x.max() <= 1.0 and y == 0
    rc = datasets.cifar100("test", label_kind="coarse")
    labels = [lab for _, lab in rc()]
    assert labels == [0, 1, 2] and rc.num_samples == 3


def test_cifar100_synthetic_fallback_is_labelled(home, monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_AUTO_DOWNLOAD", raising=False)
    r = datasets.cifar100("train", synthetic_n=16)
    assert r.is_synthetic is True and r.num_samples == 16
    labels = {lab for _, lab in r()}
    assert labels <= set(range(100))


def test_imdb_real_tarball_parsed(home):
    d = home / "imdb"
    d.mkdir(parents=True)
    buf = io.BytesIO()
    docs = {
        "aclImdb/train/pos/0_9.txt": b"a great great movie",
        "aclImdb/train/neg/0_1.txt": b"a terrible movie",
        "aclImdb/test/pos/0_8.txt": b"great stuff",
        "aclImdb/test/neg/0_2.txt": b"terrible stuff",
    }
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name, text in docs.items():
            info = tarfile.TarInfo(name)
            info.size = len(text)
            tf.addfile(info, io.BytesIO(text))
    (d / "aclImdb_v1.tar.gz").write_bytes(buf.getvalue())

    r = datasets.imdb("train", vocab_size=10)
    assert r.is_synthetic is False
    samples = list(r())
    assert len(samples) == 2
    labels = sorted(lab for _, lab in samples)
    assert labels == [0, 1]
    # 'great' appears twice in one train doc -> most frequent -> id 1;
    # both train docs share 'a'/'movie' ids; unknown-in-vocab maps to 0
    (ids_pos, _), = [s for s in samples if s[1] == 1]
    assert 1 in ids_pos
    rt = datasets.imdb("test", vocab_size=10)
    assert rt.num_samples == 2 and rt.is_synthetic is False


def test_imikolov_real_tarball_parsed(home):
    d = home / "imikolov"
    d.mkdir(parents=True)
    buf = io.BytesIO()
    train = b"the cat sat on the mat\nthe dog sat on the rug\n"
    test = b"the cat sat on the rug\n"
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name, text in (("./simple-examples/data/ptb.train.txt", train),
                           ("./simple-examples/data/ptb.test.txt", test)):
            info = tarfile.TarInfo(name)
            info.size = len(text)
            tf.addfile(info, io.BytesIO(text))
    (d / "simple-examples.tgz").write_bytes(buf.getvalue())

    r = datasets.imikolov("train", vocab=10, ngram=3)
    assert r.is_synthetic is False
    samples = list(r())
    # 2 lines x 6 tokens, ngram 3 -> 4 windows per line
    assert len(samples) == 8
    ctx, nxt = samples[0]
    assert ctx.shape == (2,)
    # 'the' is the most frequent token -> id 1; appears as first context
    assert ctx[0] == 1
    rt = datasets.imikolov("test", vocab=10, ngram=3)
    assert rt.num_samples == 4 and rt.is_synthetic is False


def test_movielens_real_zip_parsed(home):
    import zipfile
    d = home / "movielens"
    d.mkdir(parents=True)
    zpath = d / "ml-1m.zip"
    with zipfile.ZipFile(zpath, "w") as zf:
        zf.writestr("ml-1m/users.dat",
                    "1::M::25::12::12345\n2::F::35::7::54321\n")
        zf.writestr("ml-1m/movies.dat",
                    "10::Toy Story (1995)::Animation|Children's|Comedy\n"
                    "20::Heat (1995)::Action|Crime\n")
        zf.writestr("ml-1m/ratings.dat",
                    "\n".join(f"{1 + i % 2}::{10 + 10 * (i % 2)}::"
                              f"{1 + i % 5}::97830{i}"
                              for i in range(20)) + "\n")
    r = datasets.movielens("train")
    rt = datasets.movielens("test")
    assert r.is_synthetic is False and rt.is_synthetic is False
    assert r.num_samples == 18 and rt.num_samples == 2   # 90/10 split
    uid, mid, ufeat, genres, rating = next(iter(r()))
    assert ufeat.shape == (4,) and genres.shape == (6,)
    assert 1.0 <= float(rating) <= 5.0


def test_conll05_real_files_parsed(home):
    d = home / "conll05"
    d.mkdir(parents=True)
    # sentence 1: "the cat chased the mouse" — predicate 'chased',
    # A0 = "the cat", A1 = "the mouse"; sentence 2: one predicate 'sat'
    words1 = "the\ncat\nchased\nthe\nmouse\n\n"
    words2 = "dogs\nsat\n\n"
    props1 = ("-    (A0*\n-    *)\nchase    (V*)\n-    (A1*\n-    *)\n\n")
    props2 = ("-    *\nsit    (V*)\n\n")
    with gzip.open(d / "test.wsj.words.gz", "wt") as f:
        f.write(words1 + words2)
    with gzip.open(d / "test.wsj.props.gz", "wt") as f:
        f.write(props1 + props2)
    r = datasets.conll05("test", vocab=20)
    assert r.is_synthetic is False
    samples = list(r())
    assert len(samples) == 2               # one per (sentence, predicate)
    ids, pred, labels = samples[0]
    assert int(pred) == 2                  # 'chased'
    # A0 span = tokens 0-1 (B, I); A1 span = tokens 3-4 (B, I); V = O
    assert labels[2] == 0
    assert labels[0] != 0 and labels[1] == labels[0] + 1
    assert labels[3] != 0 and labels[4] == labels[3] + 1
    assert labels[0] != labels[3]
    ids2, pred2, labels2 = samples[1]
    assert int(pred2) == 1 and (labels2 == 0).all()
    # 'the' is most frequent -> id 1
    assert ids[0] == 1


def test_wmt14_real_tarball_parsed(home):
    d = home / "wmt14"
    d.mkdir(parents=True)
    buf = io.BytesIO()
    src_dict = "<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = "<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    train = "hello world\tbonjour monde\nhello\tbonjour\n"
    test = "world\tmonde\n"
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name, text in (("wmt14/src.dict", src_dict),
                           ("wmt14/trg.dict", trg_dict),
                           ("wmt14/train/train", train),
                           ("wmt14/test/test", test)):
            data = text.encode()
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    (d / "wmt14.tgz").write_bytes(buf.getvalue())

    r = datasets.wmt14("train")
    assert r.is_synthetic is False
    samples = list(r())
    assert len(samples) == 2
    src, tgt = samples[0]
    # src = <s> hello world <e> = [0, 3, 4, 1]
    np.testing.assert_array_equal(src, [0, 3, 4, 1])
    # tgt = <s> bonjour monde <e> = [0, 3, 4, 1]
    np.testing.assert_array_equal(tgt, [0, 3, 4, 1])
    rt = datasets.wmt14("test")
    assert rt.num_samples == 1 and rt.is_synthetic is False


def test_mq2007_real_letor_parsed(home):
    d = home / "mq2007"
    d.mkdir(parents=True)
    (d / "train.txt").write_text(
        "2 qid:10 1:0.1 2:0.5 #docA\n"
        "0 qid:10 1:0.3 2:0.1 #docB\n"
        "1 qid:11 1:0.9 2:0.2 #docC\n")
    r = datasets.mq2007("train")
    assert r.is_synthetic is False
    groups = list(r())
    assert len(groups) == 2
    f, rel = groups[0]
    assert f.shape == (2, 2)
    np.testing.assert_array_equal(rel, [2, 0])
    np.testing.assert_allclose(f[0], [0.1, 0.5])


def test_voc2012_real_devkit_parsed(home):
    from PIL import Image
    root = home / "voc2012" / "VOCdevkit" / "VOC2012"
    (root / "JPEGImages").mkdir(parents=True)
    (root / "Annotations").mkdir(parents=True)
    (root / "ImageSets" / "Main").mkdir(parents=True)
    Image.new("RGB", (100, 80), (120, 30, 200)).save(
        root / "JPEGImages" / "x1.jpg")
    (root / "Annotations" / "x1.xml").write_text(
        "<annotation><object><name>dog</name><bndbox>"
        "<xmin>10</xmin><ymin>8</ymin><xmax>60</xmax><ymax>40</ymax>"
        "</bndbox></object>"
        "<object><name>person</name><bndbox>"
        "<xmin>50</xmin><ymin>20</ymin><xmax>90</xmax><ymax>70</ymax>"
        "</bndbox></object></annotation>")
    (root / "ImageSets" / "Main" / "train.txt").write_text("x1\n")
    r = datasets.voc2012("train", hw=(32, 32), max_boxes=3)
    assert r.is_synthetic is False
    img, boxes, labels = next(iter(r()))
    assert img.shape == (32, 32, 3)
    np.testing.assert_allclose(boxes[0], [0.1, 0.1, 0.6, 0.5], atol=1e-6)
    from paddle_tpu.data.datasets import VOC_CLASSES
    assert labels[0] == 1 + VOC_CLASSES.index("dog")
    assert labels[1] == 1 + VOC_CLASSES.index("person")
    assert labels[2] == -1


def test_flowers_real_layout_parsed(home):
    from PIL import Image
    from scipy.io import savemat
    base = home / "flowers"
    (base / "jpg").mkdir(parents=True)
    for i in (1, 2, 3):
        Image.new("RGB", (40, 40), (i * 40, 10, 10)).save(
            base / "jpg" / f"image_{i:05d}.jpg")
    savemat(base / "imagelabels.mat",
            {"labels": np.array([[5, 7, 9]])})
    savemat(base / "setid.mat",
            {"trnid": np.array([[1, 3]]), "tstid": np.array([[2]])})
    r = datasets.flowers("train", hw=(16, 16))
    assert r.is_synthetic is False and r.num_samples == 2
    img, lab = next(iter(r()))
    assert img.shape == (16, 16, 3) and lab == 4    # label 5 -> 0-based 4
    rt = datasets.flowers("test", hw=(16, 16))
    _, lab_t = next(iter(rt()))
    assert rt.num_samples == 1 and lab_t == 6
