"""Autotuner + warmup tests (ISSUE 16): the persistent per-shape kernel
autotuner's cache contract (round-trip, corrupt/stale degrade silently,
atomic concurrent writers), the zero-overhead/bypass pins (disabled →
untimed default; explicit blocks → bit-identical, tuner never consulted),
the shared ``time_kernel`` util's compile-discard semantics, the fused
LN+matmul kernel as the first autotuned citizen, and the engine/trainer
warmup entry points (token/params-invisible, compile counts pinned)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nn import autotune
from paddle_tpu.nn.fused_ln import fused_ln_matmul, ln_matmul_reference
from paddle_tpu.nn.pallas_attention import flash_attention

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_tuner_state(monkeypatch):
    """Every test starts with the tuner off and zeroed counters, and
    never inherits a cache dir from the environment."""
    monkeypatch.delenv(autotune.ENV_VAR, raising=False)
    autotune.reset()
    autotune.reset_stats()
    yield
    autotune.reset()
    autotune.reset_stats()


def _runner_factory(costs, calls):
    """A fake kernel runner: cand ``{"b": i}`` sleeps ``costs[i]``."""
    def runner(b):
        calls.append(b)
        time.sleep(costs[b])
        return b
    return runner


# ---------------------------------------------------------------------------
# choose(): gating, round-trip, failure semantics
# ---------------------------------------------------------------------------

def test_disabled_returns_default_untimed(tmp_path):
    calls = []
    got = autotune.choose(
        "k", key="k|8|f32|cpu", candidates=[{"b": 0}, {"b": 1}],
        runner=_runner_factory([0, 0], calls), default={"b": 7})
    assert got == {"b": 7}
    assert calls == []                       # zero trials
    assert autotune.stats() == {"trials": 0, "hits": 0, "misses": 0}
    assert autotune.cache_file() is None     # zero disk I/O possible


def test_cache_round_trip(tmp_path):
    autotune.enable(str(tmp_path))
    calls = []
    key = autotune.make_key("k", shape=(4, 8), dtype="float32",
                            platform="cpu")
    kw = dict(key=key, candidates=[{"b": 0}, {"b": 1}],
              runner=_runner_factory([0.03, 0.0], calls), default={"b": 0})
    got = autotune.choose("k", **kw)
    assert got == {"b": 1}                   # the faster candidate wins
    # each candidate ran twice: one discarded compile iter + one timed
    assert sorted(set(calls)) == [0, 1]
    assert autotune.stats()["misses"] == 1
    assert autotune.stats()["trials"] == 2
    # second selection: zero trials, straight from disk
    calls.clear()
    got2 = autotune.choose("k", **kw)
    assert got2 == {"b": 1} and calls == []
    assert autotune.stats()["hits"] == 1
    # the file is a complete schema-versioned document
    with open(autotune.cache_file()) as f:
        doc = json.load(f)
    assert doc["schema"] == autotune.SCHEMA_VERSION
    assert doc["entries"][key]["config"] == {"b": 1}
    assert doc["entries"][key]["trials"] == 2


@pytest.mark.parametrize("corruption", [
    b"{not json at all",                                   # unparseable
    b'{"schema": 1, "entries": ',                          # truncated
    b'[1, 2, 3]',                                          # wrong shape
])
def test_corrupt_cache_silently_retunes(tmp_path, corruption):
    autotune.enable(str(tmp_path))
    with open(autotune.cache_file(), "wb") as f:
        f.write(corruption)
    calls = []
    got = autotune.choose(
        "k", key="kk", candidates=[{"b": 0}],
        runner=_runner_factory([0.0], calls), default={"b": 9})
    assert got == {"b": 0} and calls        # re-tuned, no exception
    with open(autotune.cache_file()) as f:  # and the file healed
        assert json.load(f)["entries"]["kk"]["config"] == {"b": 0}


def test_schema_bump_ignores_stale_entries(tmp_path):
    autotune.enable(str(tmp_path))
    stale = {"schema": autotune.SCHEMA_VERSION + 1,
             "entries": {"kk": {"config": {"b": 5}}}}
    with open(autotune.cache_file(), "w") as f:
        json.dump(stale, f)
    calls = []
    got = autotune.choose(
        "k", key="kk", candidates=[{"b": 0}],
        runner=_runner_factory([0.0], calls), default={"b": 9})
    assert got == {"b": 0}                  # NOT the stale {"b": 5}
    assert autotune.stats()["misses"] == 1
    with open(autotune.cache_file()) as f:
        doc = json.load(f)
    assert doc["schema"] == autotune.SCHEMA_VERSION
    assert "kk" in doc["entries"]


def test_all_candidates_fail_returns_default_stores_nothing(tmp_path):
    autotune.enable(str(tmp_path))

    def boom(**kw):
        raise ValueError("mis-tiled")

    got = autotune.choose("k", key="kk", candidates=[{"b": 0}, {"b": 1}],
                          runner=boom, default={"b": 7})
    assert got == {"b": 7}
    assert not os.path.exists(autotune.cache_file())   # cache not poisoned


# ---------------------------------------------------------------------------
# concurrent writers: atomic rename keeps the file a complete document
# ---------------------------------------------------------------------------

_WRITER = """
import sys
sys.path.insert(0, {repo!r})
from paddle_tpu.nn import autotune
path, key = sys.argv[1], sys.argv[2]
for i in range(120):
    autotune._store(path, key, {{"config": {{"i": i}}, "best_s": 0.0,
                                 "trials": 1, "kernel": "k"}})
print("done")
"""


def test_concurrent_writers_never_tear_the_file(tmp_path):
    path = str(tmp_path / autotune.CACHE_BASENAME)
    code = _WRITER.format(repo=REPO)
    procs = [subprocess.Popen([sys.executable, "-c", code, path, key],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for key in ("ka", "kb")]
    # hammer reads while both writers race: every observation must be
    # either no-file-yet or a COMPLETE parseable document (os.replace is
    # atomic — a torn read is the failure this test exists to catch)
    deadline = time.time() + 60
    observations = 0
    while any(p.poll() is None for p in procs) and time.time() < deadline:
        entries = autotune._load(path)      # raises on a torn read? no —
        assert isinstance(entries, dict)    # _load never raises; but a
        if os.path.exists(path):            # direct parse must succeed too
            with open(path) as f:
                json.load(f)
            observations += 1
    for p in procs:
        out, err = p.communicate(timeout=60)
        assert p.returncode == 0, err[-500:]
        assert "done" in out
    assert observations > 0
    # merge-with-disk: with 120 interleaved writes each, both keys survive
    final = autotune._load(path)
    assert set(final) == {"ka", "kb"}
    with open(path) as f:
        assert json.load(f)["schema"] == autotune.SCHEMA_VERSION


# ---------------------------------------------------------------------------
# time_kernel: the compile iteration is discarded
# ---------------------------------------------------------------------------

def test_time_kernel_discards_first_iteration():
    calls = []

    def fn():
        calls.append(len(calls))
        if len(calls) == 1:
            time.sleep(0.15)            # the "compile" hit
        return np.float32(1.0)

    wall, out = autotune.time_kernel(fn, warmup=1, iters=2, fence=None)
    assert calls == [0, 1, 2]           # 1 discarded + 2 timed
    assert wall < 0.15                  # the sleep did NOT leak into timing
    assert out == np.float32(1.0)


def test_time_kernel_fences_jax_result():
    x = jnp.ones((64, 64))
    wall, out = autotune.time_kernel(jnp.dot, x, x, warmup=1, iters=1)
    assert wall > 0
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ x))


# ---------------------------------------------------------------------------
# kernel integration: bypass + bit-identity pins
# ---------------------------------------------------------------------------

def _qkv(shape=(1, 2, 128, 16)):
    rng = np.random.RandomState(0)
    return tuple(jnp.asarray(rng.normal(size=shape).astype(np.float32))
                 for _ in range(3))


def test_explicit_blocks_bypass_tuner(tmp_path):
    q, k, v = _qkv()
    want = np.asarray(flash_attention(q, k, v, None, True, None, 64, 64,
                                      True))
    autotune.enable(str(tmp_path))
    got = np.asarray(flash_attention(q, k, v, None, True, None, 64, 64,
                                     True))
    # bit-identical AND the tuner was never consulted: no trials, no file
    assert (got == want).all()
    assert autotune.stats() == {"trials": 0, "hits": 0, "misses": 0}
    assert not os.path.exists(autotune.cache_file())


def test_tuned_flash_is_bit_identical_and_caches(tmp_path):
    q, k, v = _qkv()
    baseline = np.asarray(flash_attention(q, k, v))      # heuristic path
    autotune.enable(str(tmp_path))
    tuned = np.asarray(flash_attention(q, k, v))         # tuning path
    assert (tuned == baseline).all()    # block sizes never change math
    s = autotune.stats()
    assert s["misses"] == 1 and s["trials"] >= 1
    # warm process: same call is a pure cache hit
    autotune.reset_stats()
    tuned2 = np.asarray(flash_attention(q, k, v))
    assert (tuned2 == baseline).all()
    assert autotune.stats() == {"trials": 0, "hits": 1, "misses": 0}
    entries = autotune._load(autotune.cache_file())
    assert any(k_.startswith("flash_fwd|") for k_ in entries)


def test_fused_ln_matmul_matches_reference(tmp_path):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.normal(size=(128, 32)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(32, 256)).astype(np.float32))
    scale = jnp.asarray(rng.normal(size=(32,)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(32,)).astype(np.float32))
    for sc, bi in ((None, None), (scale, None), (scale, bias)):
        got = fused_ln_matmul(x, w, sc, bi)
        want = ln_matmul_reference(x, w, sc, bi)
        # f32-roundoff match, not bit-identity: the fused kernel body is
        # one XLA computation, whose FMA contraction can differ by 1 ulp
        # from the op-at-a-time eager reference
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
    # the autotuned path selects a dividing config and persists it
    autotune.enable(str(tmp_path))
    got = fused_ln_matmul(x, w, scale, bias)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ln_matmul_reference(x, w, scale, bias)),
        rtol=1e-5, atol=1e-5)
    entries = autotune._load(autotune.cache_file())
    assert any(k_.startswith("ln_matmul|") for k_ in entries)
    cfg = next(v["config"] for k_, v in entries.items()
               if k_.startswith("ln_matmul|"))
    assert 128 % cfg["block_m"] == 0 and 256 % cfg["block_n"] == 0


# ---------------------------------------------------------------------------
# warmup entry points (engine + trainer)
# ---------------------------------------------------------------------------

def test_engine_warmup_invisible_and_counts_pinned():
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.serve import DecodeEngine

    V, W = 64, 24
    model = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                          ffn_hidden=64, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))

    def run(warm):
        eng = DecodeEngine(model, vs, max_slots=2, block_size=4)
        if warm:
            rep = eng.warmup()
            assert rep["compile_counts"] == {"prefill": 1, "tick": 1}
            assert rep["wall_s"] > 0
            # no cache dirs configured → tri-state Nones, zero trials
            assert rep["autotune_trials"] == 0
            assert rep["autotune_cache_hit"] is None
            assert rep["xla_cache_hit"] is None
        eng.admit(0, [3, 1, 4, 1], reserve_len=12)
        toks = [int(eng.decode_tick()[0]) for _ in range(6)]
        assert eng.compile_counts() == {"prefill": 1, "tick": 1}
        return toks

    assert run(warm=True) == run(warm=False)   # warmup is token-invisible


def test_trainer_warmup_aot_reports():
    from paddle_tpu import optim
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    rng = np.random.RandomState(0)
    batch = {"x": rng.normal(size=(8, 784)).astype(np.float32),
             "label": rng.randint(0, 10, (8,)).astype(np.int32)}
    tr = Trainer(model=MnistMLP(),
                 loss_fn=lambda out, b: costs.softmax_cross_entropy(
                     out, b["label"]),
                 optimizer=optim.sgd(0.1))
    tr.init(jax.random.PRNGKey(0), batch)
    before = jax.tree_util.tree_map(np.asarray, tr.train_state.params)
    rep = tr.warmup([batch])
    assert rep["wall_s"] > 0 and rep["fingerprint"]
    assert rep["cache_hit"] is None            # no XLA cache configured
    assert rep["autotune_trials"] == 0
    # AOT-only: warmup must not step the optimizer
    after = jax.tree_util.tree_map(np.asarray, tr.train_state.params)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        assert (a == b).all()
