"""CI gate for the fused train-step pipeline: ``bench.py --smoke`` must run
green on CPU and report the fused-vs-plain differential (ISSUE 1 satellite:
the fused path cannot rot without tier-1 noticing) AND the telemetry block
(ISSUE 2 satellite: a telemetry-on CPU training must emit JSONL that parses
and carries the required schema keys)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_cpu_green_and_equal():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)          # plain single-device CPU
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr[-800:]
    out = json.loads(res.stdout.strip().splitlines()[-1])
    assert out["metric"] == "fused_vs_plain_smoke"
    assert out["equal"] is True
    assert out["params_equal"] is True and out["losses_equal"] is True
    assert out["K"] == 4 and out["M"] == 2
    # the differential is the point: both per-step times present and sane
    assert out["fused_ms_per_opt_step"] > 0
    assert out["plain_ms_per_opt_step"] > 0
    assert np.isfinite(out["final_loss"])
    # ISSUE 2: the telemetry gate ran, its JSONL parsed with the required
    # keys, and attaching telemetry did not perturb the training math
    tel = out["telemetry"]
    assert tel["jsonl_ok"] is True, tel
    assert tel["losses_equal_with_telemetry"] is True
    assert tel["jsonl_records"] > 0 and tel["steps_emitted"] > 0
    assert tel["compile_count"] >= 1 and tel["retrace_count"] >= 0
    # step breakdown + MFU accounting carried into the BENCH snapshot
    assert tel["mean_dispatch_ms"] > 0 and tel["mean_device_ms"] > 0
    assert tel["hlo_flops_per_call"] and tel["hlo_flops_per_call"] > 0
    assert tel["tokens_per_sec"] > 0
    assert tel["grad_norm"] > 0
    # ISSUE 4: the structured-trace gate ran — the traced pipelined run
    # serialized a valid Chrome trace with spans from >=2 threads, every
    # staging flow paired with its drain, sane monotonic timestamps, a
    # staging span provably concurrent with a main-thread span, and no
    # math perturbation
    trace = out["trace"]
    assert trace["trace_ok"] is True, trace
    assert trace["threads"] >= 2 and trace["spans"] > 0
    assert trace["flows"] >= 1 and trace["flows_paired"] is True
    assert trace["ts_monotonic"] is True and trace["ts_valid"] is True
    assert trace["stage_concurrent_with_main"] is True
    assert trace["losses_equal_with_tracer"] is True
    # ISSUE 6: the attribution gate ran on a simulated dp mesh — >= 4
    # named scopes with nonzero FLOPs, parsed total within 5% of
    # cost_analysis(), a collective inventory, and an exposed-
    # communication estimate for the grad all-reduce
    attr = out["attribution"]
    assert attr["ok"] is True, attr
    assert attr["n_devices"] == 2
    assert attr["scopes_nonzero"] >= 4
    assert abs(attr["flops_vs_cost_analysis_pct"]) <= 5.0
    assert attr["collectives"] >= 1
    gar = attr["grad_allreduce"]
    assert gar["ops"] >= 1 and gar["wire_bytes_per_device"] > 0
    assert gar["exposed_ms_if_overlapped"] is not None
    assert attr["emitted_records"] == 1
    # ISSUE 8: the gradient-sync overlap gate ran on the same simulated
    # dp mesh — bucketed and fused modes train bit-identically, the
    # bucketed HLO carries >= 2 gradient all-reduces (vs exactly 1
    # fused) including the per-layer in-scan sync, and the attribution
    # record's per-bucket comm rows carry the sched_distance field
    ovl = out["overlap"]
    assert ovl["ok"] is True, ovl
    assert ovl["n_devices"] == 2
    assert ovl["losses_equal"] is True and ovl["params_equal"] is True
    assert ovl["bucketed_grad_allreduces"] >= 2
    assert ovl["fused_grad_allreduces"] == 1
    assert ovl["in_scan_rows"] >= 1
    assert ovl["sched_distance_field"] is True
    assert ovl["emitted_records"] == 1
    # ISSUE 9: the serving gate ran — 8 ragged requests complete under
    # both policies, the compiled prefill/tick never retrace across
    # admission/eviction churn, per-request TTFT/TPOT telemetry records
    # are emitted, continuous batching beats the gang-static baseline on
    # ragged-length tokens/sec, and the decode tick's attribution
    # classifies decode/* as memory-bound
    srv = out["serving"]
    assert srv["ok"] is True, srv
    assert srv["continuous"]["completed"] == 8
    assert srv["static"]["completed"] == 8
    assert srv["zero_retraces_after_warmup"] is True
    assert srv["continuous"]["compile_counts"] == {"prefill": 1, "tick": 1}
    assert srv["continuous"]["request_records"] == 16
    assert srv["continuous"]["sample_request"]["ttft_ms"] is not None
    assert srv["continuous"]["sample_request"]["tpot_ms"] is not None
    assert (srv["continuous"]["tokens_per_sec"]
            > srv["static"]["tokens_per_sec"])
    assert srv["continuous"]["ticks"] < srv["static"]["ticks"]
    assert srv["decode_bound"] == "memory"
    # ISSUE 12: the serving-throughput legs — a shared-prefix workload
    # admits with FEWER fresh block allocations than sharing-off
    # (bit-identical tokens, zero leaks after full churn), speculative
    # greedy decode is token-identical with strictly fewer ticks and
    # the compile counts stay pinned, and chunked prefill interleaves a
    # long admission with running slots' decode ticks instead of
    # stalling them
    ps = srv["prefix_sharing"]
    assert ps["ok"] is True and ps["tokens_identical"] is True
    assert ps["fresh_allocs_shared"] < ps["fresh_allocs_unshared"]
    assert ps["leak_free"] is True and ps["prefix_hit_blocks"] >= 1
    sp = srv["speculative"]
    assert sp["ok"] is True and sp["tokens_identical"] is True
    assert sp["ticks_speculative"] < sp["ticks_baseline"]
    assert sp["compile_counts"] == {"prefill": 1, "tick": 1}
    ck = srv["chunked_prefill"]
    assert ck["ok"] is True and ck["tokens_identical"] is True
    assert (ck["interleaved_tokens_chunked"]
            > ck["interleaved_tokens_monolithic"])
    assert ck["compile_counts"] == {"prefill": 1, "tick": 1}
    # ISSUE 14: the quantization leg — at EQUAL pool bytes the int8
    # pool admits >= 1.8x the resident sequences, a saturated workload
    # completes every request, and greedy tokens agree >= 99% with the
    # f32 pool (the bounded-drift acceptance criterion)
    qz = srv["quantization"]
    assert qz["ok"] is True, qz
    assert qz["capacity_ratio"] >= 1.8
    assert qz["resident_int8"] >= qz["resident_f32"]
    assert qz["completed"] == 8
    assert qz["token_agreement"] >= 0.99
    assert qz["kv_bytes_per_token_int8"] < qz["kv_bytes_per_token_f32"]
    assert qz["compile_counts"] == {"prefill": 1, "tick": 1}
    # ISSUE 14: the retention leg — a second wave of same-prefix
    # sessions (no live sharer) hits the retained LRU, allocates fewer
    # fresh blocks than a retention-off engine, and leaks nothing
    rt = srv["retention"]
    assert rt["ok"] is True, rt
    assert rt["retained_hits"] >= 1
    assert (rt["wave2_fresh_allocs_retained"]
            < rt["wave2_fresh_allocs_unretained"])
    assert rt["leak_free"] is True
    assert rt["compile_counts"] == {"prefill": 1, "tick": 1}
    # ISSUE 15: the tensor-parallel leg — the tp=2 engine (2 forced
    # host devices) is token-identical to the single-device engine
    # across two churn waves on ONE engine (zero retraces after
    # warmup), per-shard KV bytes halve so the per-device capacity
    # ratio is >= 2, the tick's tp collectives classify into the
    # serving comm table, and nothing leaks
    tpl = srv["tp"]
    assert tpl["ok"] is True, tpl
    assert tpl["tokens_identical"] is True
    assert tpl["tp_degree"] == 2
    assert tpl["compile_counts"] == {"prefill": 1, "tick": 1}
    assert tpl["per_shard_capacity_ratio"] >= 2.0
    assert (tpl["kv_bytes_per_token_tp"] * 2
            == tpl["kv_bytes_per_token_1dev"])
    assert tpl["decode_comm_ops"] >= 1
    assert tpl["leak_free"] is True
    # ISSUE 10: the fault-tolerance gate ran — the supervisor resumed an
    # injected crash, a corrupted latest pass was quarantined (renamed
    # .corrupt, never deleted) with fallback to the previous readable
    # pass, and a mid-pass preemption quiesced with the distinct
    # "preempted" status then resumed — each leg's final params
    # BIT-EQUAL (f32) to the uninterrupted run
    flt = out["faults"]
    assert flt["ok"] is True, flt
    assert flt["crash"]["status"] == "completed"
    assert flt["crash"]["restarts"] == 1
    assert flt["crash"]["params_equal"] is True
    assert flt["corrupt"]["status"] == "completed"
    assert flt["corrupt"]["fallbacks"] >= 1
    assert flt["corrupt"]["corrupt_dirs"] >= 1
    assert flt["corrupt"]["params_equal"] is True
    assert flt["preempt"]["first_status"] == "preempted"
    assert flt["preempt"]["preempt_next_batch"] is not None
    assert flt["preempt"]["second_status"] == "completed"
    assert flt["preempt"]["params_equal"] is True
    # ISSUE 11: the serving-fleet gate ran — a seeded bursty loadgen
    # trace over 3 replicas survived one injected replica kill (detected
    # via heartbeat staleness, requests resubmitted with retried
    # lineage) and one mid-traffic drain; every request terminal with
    # exactly one terminal record per rid, no KV-block leaks and zero
    # retraces on survivors, p99 TTFT finite, shedding bounded, and SJF
    # beats FCFS on goodput-under-deadline via the percentile metrics
    fl = out["fleet"]
    assert fl["ok"] is True, fl
    assert fl["all_terminal"] is True and fl["lineage_ok"] is True
    assert fl["no_leak_on_survivors"] is True
    assert fl["zero_retraces_on_survivors"] is True
    assert fl["p99_ttft_finite"] is True and fl["shed_bounded"] is True
    assert fl["stats"]["resubmits"] >= 1
    assert fl["stats"]["stale_completions"] == 0
    assert "kill_replica_at_tick" in fl["faults_fired"]
    assert fl["requests"]["ttft_ms_p99"] is not None
    assert fl["sjf_beats_fcfs_goodput"] is True
    assert fl["goodput_sjf_pct"] > fl["goodput_fcfs_pct"]
    # ISSUE 13: the process-isolation leg — replicas as REAL child
    # processes behind the submit/complete transport. A SIGKILL'd
    # subprocess replica mid-decode is contained (router alive, death
    # observed via heartbeat staleness): all requests terminal with
    # exactly one terminal record per rid and oracle-identical tokens,
    # live survivors leak- and retrace-free by their own stats probes,
    # an injected transport hang recovers through the per-message
    # timeout + at-least-once retransmit, a garbled reply is classified
    # (not a crash), and the autoscaler cold-spawns a replacement
    # within its restart budget
    pr = fl["process"]
    assert pr["ok"] is True, pr
    assert pr["all_terminal"] is True and pr["lineage_ok"] is True
    assert pr["oracle_tokens_ok"] is True
    assert pr["no_leak_on_survivors"] is True
    assert pr["zero_retraces_on_survivors"] is True
    assert pr["transport_hang_recovered"] is True
    assert pr["corrupt_reply_classified"] is True
    assert pr["replacement_spawned"] is True
    assert pr["replacements_within_budget"] == 1
    assert pr["retried_requests"] >= 1
    assert pr["stats"]["stale_completions"] == 0
    assert pr["stats"]["replica_mode"] == "process"
    assert {"sigkill_replica_at_tick", "transport_hang_at",
            "corrupt_reply_at"} <= set(pr["faults_fired"])
    assert any(e["action"] == "replace" for e in pr["scale_events"])
    # ISSUE 17: the observability leg — the SIGKILL-resubmit drill run
    # instrumented (tracing + SLO + serving anomaly forensics + child
    # JSONL sinks) and dark. The merged fleet trace Chrome-parses with
    # the router lane plus >= 2 replica lanes, the killed-and-
    # resubmitted rid is ONE connected s->t->f flow across processes,
    # the streaming SLO report has finite p99s and publishes a burn
    # rate through stats(), the injected stall fires tick_stall with a
    # forensic bundle, the SIGKILLed child's line-flushed JSONL
    # outlives its process, and the instrumented run's tokens/finish
    # reasons are identical to the dark run's (zero observer effect)
    tg = fl["tracing"]
    assert tg["ok"] is True, tg
    assert 0 in tg["lanes"] and len([p for p in tg["lanes"] if p > 0]) >= 2
    assert tg["resubmitted_rids"] and tg["resubmit_flow_connected"] is True
    assert tg["lane_monotonic"] is True
    assert tg["trace_events"] > 0
    assert tg["slo"]["wall_ms_p99"] is not None
    assert tg["slo"]["burn_rate"] is not None
    assert tg["tick_stall_fired"] is True
    assert tg["anomaly_bundle"] is True
    assert tg["killed_child_jsonl_survives"] is True
    assert tg["identical_to_uninstrumented"] is True
    # ISSUE 18: the disaggregation leg — 1 prefill + 2 decode replicas
    # as SOCKET children on loopback: every request prefills on the
    # prefill replica, streams its KV pages over TCP as CRC-checked
    # binary frames, and decodes the greedy oracle's exact tokens; the
    # wire bytes equal blocks x the analytic per-block size. The
    # in-process differentials pin the claim: decode tokens/tick holds
    # within 25% when heavy prefill-only load is added, and int8 KV
    # crosses the wire quantized (identical tokens to colocated int8,
    # ~2.7x fewer bytes per block than f32)
    dg = fl["disagg"]
    assert dg["ok"] is True, dg
    assert dg["socket_all_terminal"] is True
    assert dg["socket_oracle_tokens"] is True
    assert dg["socket_role_placement"] is True
    assert dg["socket_wire_bytes_exact"] is True
    assert dg["socket_handoffs"] >= 6 and dg["socket_wire_bytes"] > 0
    assert dg["router_ms"]["total"] > 0.0
    assert dg["decode_isolated_under_prefill_load"] is True
    assert dg["decode_isolation_ratio"] >= 0.75
    assert dg["int8_tokens_identical_to_colocated"] is True
    assert dg["int8_wire_bytes_exact"] is True
    assert dg["int8_wire_ratio_vs_f32"] == pytest.approx(8 / 3)
    # ISSUE 20: the chaos leg — the disagg socket fleet under a seeded
    # NetworkChaos plane. An asymmetric partition (child hears the
    # parent, parent hears nothing) falsely kills the only prefill
    # replica -> epoch fence -> disagg degrades to colocated prefill on
    # the decoders and RELEASES on heal; a one-shot flap window fences
    # a decode replica the same way. Both zombies re-admit under fresh
    # leases having generated ZERO tokens under their revoked epochs,
    # every rid keeps exactly one terminal record with oracle tokens,
    # survivors are leak-free, and the chaos-off leg-5a socket fleet is
    # the dark twin: its stats() schema differs by exactly {"chaos"}
    cz = fl["chaos"]
    assert cz["ok"] is True, cz
    assert cz["all_terminal"] is True and cz["single_lineage"] is True
    assert cz["oracle_tokens"] is True
    assert cz["fences"] >= 2
    assert cz["readmitted"] >= cz["fences"]
    assert cz["zero_tokens_while_fenced"] is True
    assert cz["survivors_leak_free"] is True
    assert cz["degradation_engaged_and_released"] is True
    assert cz["membership"]["degradations"] >= 1
    assert cz["network"]["frames_dropped"] > 0
    assert cz["network"]["drop_reasons"]["partition"] > 0
    assert cz["network"]["drop_reasons"]["flap"] > 0
    assert cz["stats_keys_vs_dark_twin"] == ["chaos"]
    # ISSUE 16: the cold-vs-warm spawn gate ran — two fresh replica
    # children against one cache root. The cold child pays >= 1 autotune
    # trial and misses both persistent caches; the warm child runs ZERO
    # trials, hits the autotune JSON and the XLA compile cache, and
    # comes up strictly faster to hello; both keep compile_counts
    # pinned at {prefill: 1, tick: 1} through real traffic and emit
    # identical tokens (warmup + caches are semantically invisible)
    sp = out["spawn"]
    assert sp["ok"] is True, sp
    assert sp["cold_tuned"] is True
    assert sp["cold_autotune_miss"] is True and sp["cold_xla_miss"] is True
    assert sp["warm_zero_trials"] is True
    assert sp["warm_autotune_hit"] is True and sp["warm_xla_hit"] is True
    assert sp["token_identical"] is True
    assert sp["compile_counts_pinned"] is True
    assert sp["warm_faster_hello"] is True
    assert sp["cold_ttft_s"] > 0 and sp["warm_ttft_s"] > 0
    assert sp["cold_startup_ms"]["total"] > 0
    assert sp["warm_startup_ms"]["xla_cache_entries_added"] == 0
    assert sp["spawn_speedup"] > 1.0


def _write_bench(tmp_path, name, metrics):
    """A minimal compact-format bench record file."""
    doc = {"metric": "x", "metrics": metrics}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_bench_compare_detects_regressions(tmp_path):
    """ISSUE 6 satellite: --compare diffs two bench records per metric
    with unit-aware direction and a configurable threshold; regressions
    exit non-zero so CI can gate on the BENCH trajectory."""
    sys.path.insert(0, REPO)
    import bench
    old = _write_bench(tmp_path, "old.json", {
        "throughput": {"v": 1000.0, "u": "images/sec"},
        "latency": {"v": 100.0, "u": "ms/batch"},
        "steady": {"v": 50.0, "u": "tokens/sec"},
        "gone": {"v": 1.0, "u": "steps/sec"},
    })
    new = _write_bench(tmp_path, "new.json", {
        "throughput": {"v": 900.0, "u": "images/sec"},   # -10%: regression
        "latency": {"v": 90.0, "u": "ms/batch"},         # lower ms: improved
        "steady": {"v": 51.0, "u": "tokens/sec"},        # +2%: ok
        "fresh": {"v": 2.0, "u": "steps/sec"},           # new metric
    })
    out = bench.compare_bench(old, new, threshold_pct=5.0)
    rows = out["rows"]
    assert rows["throughput"]["status"] == "regressed"
    assert rows["latency"]["status"] == "improved"
    assert rows["latency"]["direction"] == "lower-better"
    assert rows["steady"]["status"] == "ok"
    assert rows["fresh"]["status"] == "new"
    assert rows["gone"]["status"] == "missing"
    assert sorted(out["regressions"]) == ["gone", "throughput"]
    assert out["ok"] is False
    # a ms-metric that RISES past threshold regresses
    out2 = bench.compare_bench(new, old, threshold_pct=5.0)
    assert out2["rows"]["latency"]["status"] == "regressed"
    # threshold is configurable: 15% tolerates the -10%
    out3 = bench.compare_bench(old, new, threshold_pct=15.0)
    assert "throughput" not in out3["regressions"]


def test_bench_compare_cli_exit_codes(tmp_path, capsys, monkeypatch):
    """The --compare entry point exits 1 on regression, 0 when clean
    (in-process through bench.main — the dispatch runs before any jax
    work, so no subprocess is needed)."""
    sys.path.insert(0, REPO)
    import bench
    old = _write_bench(tmp_path, "o.json",
                       {"m": {"v": 100.0, "u": "tokens/sec"}})
    bad = _write_bench(tmp_path, "b.json",
                       {"m": {"v": 10.0, "u": "tokens/sec"}})
    same = _write_bench(tmp_path, "s.json",
                        {"m": {"v": 101.0, "u": "tokens/sec"}})
    monkeypatch.setattr(sys, "argv", ["bench.py", "--compare", old, bad])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 1
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["regressions"] == ["m"]
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--compare", old, same,
                         "--threshold", "5"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0


def test_bench_prep_transformer_fused_builds():
    """The device-bench fused metric prep wires Trainer's fused dispatch
    into the harness step protocol; one tiny-config call must run and
    advance K optimizer steps."""
    sys.path.insert(0, REPO)
    import jax
    import bench
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy

    with use_policy(bfloat16_compute):
        # batch divides the conftest 8-device data mesh
        step_body, state0, meta = bench.prep_transformer_fused(
            batch_size=8, seq_len=16, dim=32, layers=2, heads=2, vocab=64,
            k_steps=3)
        state = jax.jit(step_body)(state0)
    assert int(state[3]) == 3                    # K steps per call
    assert np.isfinite(float(state[-1]))
    assert meta["units_per_step"] == 3 * 8 * 16


def test_bench_serving_child_builds(capsys):
    """ISSUE 9: the transformer_decode metric child runs at a tiny config
    — steady-state ticks through the real engine, one compiled program
    per entry point, sane tokens/sec."""
    sys.path.insert(0, REPO)
    import bench
    bench.run_serving_bench_child(
        max_slots=2, block_size=4, seq_len=64, dim=32, layers=2, heads=4,
        vocab=64, prompt_len=8, warmup_ticks=2, timed_ticks=6)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["child"] == "transformer_decode"
    assert out["decode_tokens_per_sec"] > 0
    assert out["compile_counts"] == {"prefill": 1, "tick": 1}
    assert out["context_width"] == 64


def test_bench_serving_int8_child_builds(capsys):
    """ISSUE 14: the transformer_decode_int8 metric child runs at a tiny
    config — the same steady-state tick over a quantized pool, programs
    pinned, KV bytes/token strictly below the f32 accounting."""
    sys.path.insert(0, REPO)
    import bench
    bench.run_serving_bench_child(
        max_slots=2, block_size=4, seq_len=64, dim=32, layers=2, heads=4,
        vocab=64, prompt_len=8, warmup_ticks=2, timed_ticks=6,
        kv_dtype="int8")
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["child"] == "transformer_decode_int8"
    assert out["decode_tokens_per_sec"] > 0
    assert out["compile_counts"] == {"prefill": 1, "tick": 1}
    assert out["kv_dtype"] == "int8"
    # int8 values + one f32 scale per head vs 4 bytes per element
    assert out["kv_bytes_per_token"] < 2 * 2 * 4 * 8 * 4


def test_bench_serving_spec_child_builds(capsys):
    """ISSUE 12: the transformer_decode_spec metric child runs at a tiny
    config — the speculative engine retires MORE tokens than ticks
    (accepted drafts), matches the plain engine's program pins, and
    reports a finite accept rate on the draft-friendly periodic
    workload."""
    sys.path.insert(0, REPO)
    import bench
    bench.run_serving_spec_bench_child(
        max_slots=2, block_size=4, seq_len=64, dim=32, layers=2, heads=4,
        vocab=64, prompt_len=8, speculative=3, warmup_ticks=2,
        timed_ticks=6)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["child"] == "transformer_decode_spec"
    assert out["decode_spec_tokens_per_sec"] > 0
    assert out["spec"]["compile_counts"] == {"prefill": 1, "tick": 1}
    assert out["base"]["compile_counts"] == {"prefill": 1, "tick": 1}
    # periodic prompts: drafts hit, so a tick retires > 1 token/slot
    assert out["spec"]["tokens"] > out["base"]["tokens"]
    assert out["draft_accept_rate"] is not None
    assert 0 < out["draft_accept_rate"] <= 1


def test_bench_serving_tp_child_builds(capsys):
    """ISSUE 15: the transformer_decode_tp metric child runs at a tiny
    config on the conftest 8-device CPU platform — the steady-state tick
    over a 2-device tensor-parallel mesh with the programs pinned and
    the PER-SHARD KV accounting at half the single-device bytes."""
    sys.path.insert(0, REPO)
    import bench
    bench.run_serving_tp_bench_child(
        max_slots=2, block_size=4, seq_len=64, dim=32, layers=2, heads=4,
        vocab=64, prompt_len=8, warmup_ticks=2, timed_ticks=6)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["child"] == "transformer_decode_tp"
    assert out["decode_tokens_per_sec"] > 0
    assert out["tp_degree"] == 2
    assert out["compile_counts"] == {"prefill": 1, "tick": 1}
    # half the heads per shard: 2 * L(2) * H_local(2) * hd(8) * 4 bytes
    assert out["kv_bytes_per_token_per_shard"] == 2 * 2 * 2 * 8 * 4


def test_bench_prep_transformer_dp_overlap_builds():
    """ISSUE 8: the dp-overlap metric prep builds the bucketed-sync
    trainer on the 8-device data mesh (explicit sync active) and one
    call advances K optimizer steps."""
    sys.path.insert(0, REPO)
    import jax
    import bench

    step_body, state0, meta = bench.prep_transformer_dp_overlap(
        batch_size=8, seq_len=16, dim=32, layers=2, heads=2, vocab=64,
        k_steps=2, bucket_mb=0.001)
    state = jax.jit(step_body)(state0)
    assert int(state[3]) == 2
    assert np.isfinite(float(state[-1]))
    assert meta["grad_sync_active"] == "bucketed"
    assert meta["units_per_step"] == 2 * 8 * 16
