"""Evaluator tests vs brute-force oracles (the analog of the reference's
evaluator unit tests in ``paddle/gserver/tests/test_Evaluator.cpp``)."""

import numpy as np
import pytest

from paddle_tpu.train.evaluators import ChunkEvaluator, PnPair, RankAuc


# ------------------------------------------------------------------ rankauc

def _auc_oracle(scores, labels):
    """O(n^2) pairwise AUC with tie credit 0.5."""
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    diff = pos[:, None] - neg[None, :]
    return ((diff > 0).sum() + 0.5 * (diff == 0).sum()) / diff.size


@pytest.mark.parametrize("seed", range(3))
def test_rankauc_matches_pairwise_oracle(seed):
    rng = np.random.RandomState(seed)
    scores = np.round(rng.normal(size=200), 1)      # rounding forces ties
    labels = rng.randint(0, 2, size=200)
    ev = RankAuc()
    # stream in three chunks
    for lo, hi in [(0, 70), (70, 150), (150, 200)]:
        ev.update({"score": scores[lo:hi], "label": labels[lo:hi],
                   "weight": np.ones(hi - lo)})
    got = ev.result()["rankauc"]
    assert abs(got - _auc_oracle(scores, labels)) < 1e-9


def test_rankauc_degenerate():
    ev = RankAuc()
    ev.update({"score": np.array([0.3, 0.7]), "label": np.array([1, 1]),
               "weight": np.ones(2)})
    assert ev.result()["rankauc"] == 0.5


# ------------------------------------------------------------------- pnpair

def test_pnpair_grouped():
    ev = PnPair()
    # query 0: pos 0.9 vs negs (0.1, 0.9) -> 1 correct, 1 tie
    # query 1: pos 0.2 vs neg 0.5 -> 1 wrong
    ev.update({"score": np.array([0.9, 0.1, 0.9, 0.2, 0.5]),
               "label": np.array([1, 0, 0, 1, 0]),
               "query": np.array([0, 0, 0, 1, 1])})
    res = ev.result()
    assert res["pnpair_pairs"] == 3
    assert abs(res["pnpair"] - (1 + 0.5) / 3) < 1e-12


# ------------------------------------------------------------------- chunk

def _oracle_chunks(tags, length, num_types):
    """Independent IOB oracle following the reference's isChunkBegin/isChunkEnd
    (ChunkEvaluator.cpp:236): B- begins; I-k begins when no k-span is active."""
    chunks = []
    start = typ = None
    for t in range(length):
        tag = int(tags[t])
        is_o = tag >= 2 * num_types
        tt = None if is_o else tag // 2
        is_b = (not is_o) and tag % 2 == 0
        if start is not None and (is_o or is_b or tt != typ):
            chunks.append((start, t - 1, typ))
            start = typ = None
        if not is_o and start is None:
            start, typ = t, tt
    if start is not None:
        chunks.append((start, length - 1, typ))
    return set(chunks)


def test_chunk_begin_on_i_after_o():
    """I-tag after O opens a chunk (malformed sequences), matching conlleval."""
    ev = ChunkEvaluator(num_tag_types=2)
    # tags: B-0=0 I-0=1 B-1=2 I-1=3 O=4
    pred = np.array([[4, 1, 1, 4, 3]])          # O I-0 I-0 O I-1
    gold = np.array([[0, 1, 1, 4, 2]])          # B-0 I-0 I-0 O B-1
    ev.update({"pred": pred, "gold": gold, "length": np.array([5])})
    # pred chunks: (1,2,0),(4,4,1); gold chunks: (0,2,0),(4,4,1) → 1 correct
    assert ev._pred == 2 and ev._gold == 2 and ev._correct == 1


def test_chunk_i_after_different_type_begins():
    def spans(tags):
        ev = ChunkEvaluator(num_tag_types=3)
        arr = np.array([tags])
        ev.update({"pred": arr, "gold": arr,
                   "length": np.array([len(tags)])})
        return ev._pred, _oracle_chunks(np.array(tags), len(tags), 3)

    # B-0 I-1 (type switch inside) → two chunks
    assert spans([0, 3]) == (2, {(0, 0, 0), (1, 1, 1)})
    # B-0 B-0 → two chunks
    assert spans([0, 0]) == (2, {(0, 0, 0), (1, 1, 0)})
    # I-2 at t=0 begins → one chunk
    assert spans([5, 5]) == (1, {(0, 1, 2)})


@pytest.mark.parametrize("seed", range(5))
def test_chunk_vectorized_matches_oracle(seed):
    """Vectorized batch extraction == per-token oracle on random tag soup."""
    rng = np.random.RandomState(seed)
    num_types = 3
    B, T = 8, 17
    pred = rng.randint(0, 2 * num_types + 1, size=(B, T))
    gold = rng.randint(0, 2 * num_types + 1, size=(B, T))
    lengths = rng.randint(0, T + 1, size=(B,))
    ev = ChunkEvaluator(num_tag_types=num_types)
    ev.update({"pred": pred, "gold": gold, "length": lengths})
    correct = npred = ngold = 0
    for b in range(B):
        pc = _oracle_chunks(pred[b], lengths[b], num_types)
        gc = _oracle_chunks(gold[b], lengths[b], num_types)
        correct += len(pc & gc)
        npred += len(pc)
        ngold += len(gc)
    assert (ev._correct, ev._pred, ev._gold) == (correct, npred, ngold)
