"""Evaluator tests vs brute-force oracles (the analog of the reference's
evaluator unit tests in ``paddle/gserver/tests/test_Evaluator.cpp``)."""

import numpy as np
import pytest

from paddle_tpu.train.evaluators import ChunkEvaluator, PnPair, RankAuc


# ------------------------------------------------------------------ rankauc

def _auc_oracle(scores, labels):
    """O(n^2) pairwise AUC with tie credit 0.5."""
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    diff = pos[:, None] - neg[None, :]
    return ((diff > 0).sum() + 0.5 * (diff == 0).sum()) / diff.size


@pytest.mark.parametrize("seed", range(3))
def test_rankauc_matches_pairwise_oracle(seed):
    rng = np.random.RandomState(seed)
    scores = np.round(rng.normal(size=200), 1)      # rounding forces ties
    labels = rng.randint(0, 2, size=200)
    ev = RankAuc()
    # stream in three chunks
    for lo, hi in [(0, 70), (70, 150), (150, 200)]:
        ev.update({"score": scores[lo:hi], "label": labels[lo:hi],
                   "weight": np.ones(hi - lo)})
    got = ev.result()["rankauc"]
    assert abs(got - _auc_oracle(scores, labels)) < 1e-9


def test_rankauc_degenerate():
    ev = RankAuc()
    ev.update({"score": np.array([0.3, 0.7]), "label": np.array([1, 1]),
               "weight": np.ones(2)})
    assert ev.result()["rankauc"] == 0.5


# ------------------------------------------------------------------- pnpair

def test_pnpair_grouped():
    ev = PnPair()
    # query 0: pos 0.9 vs negs (0.1, 0.9) -> 1 correct, 1 tie
    # query 1: pos 0.2 vs neg 0.5 -> 1 wrong
    ev.update({"score": np.array([0.9, 0.1, 0.9, 0.2, 0.5]),
               "label": np.array([1, 0, 0, 1, 0]),
               "query": np.array([0, 0, 0, 1, 1])})
    res = ev.result()
    assert res["pnpair_pairs"] == 3
    assert abs(res["pnpair"] - (1 + 0.5) / 3) < 1e-12


# ------------------------------------------------------------------- chunk

def _oracle_chunks(tags, length, num_types):
    """Independent IOB oracle following the reference's isChunkBegin/isChunkEnd
    (ChunkEvaluator.cpp:236): B- begins; I-k begins when no k-span is active."""
    chunks = []
    start = typ = None
    for t in range(length):
        tag = int(tags[t])
        is_o = tag >= 2 * num_types
        tt = None if is_o else tag // 2
        is_b = (not is_o) and tag % 2 == 0
        if start is not None and (is_o or is_b or tt != typ):
            chunks.append((start, t - 1, typ))
            start = typ = None
        if not is_o and start is None:
            start, typ = t, tt
    if start is not None:
        chunks.append((start, length - 1, typ))
    return set(chunks)


def test_chunk_begin_on_i_after_o():
    """I-tag after O opens a chunk (malformed sequences), matching conlleval."""
    ev = ChunkEvaluator(num_tag_types=2)
    # tags: B-0=0 I-0=1 B-1=2 I-1=3 O=4
    pred = np.array([[4, 1, 1, 4, 3]])          # O I-0 I-0 O I-1
    gold = np.array([[0, 1, 1, 4, 2]])          # B-0 I-0 I-0 O B-1
    ev.update({"pred": pred, "gold": gold, "length": np.array([5])})
    # pred chunks: (1,2,0),(4,4,1); gold chunks: (0,2,0),(4,4,1) → 1 correct
    assert ev._pred == 2 and ev._gold == 2 and ev._correct == 1


def test_chunk_i_after_different_type_begins():
    def spans(tags):
        ev = ChunkEvaluator(num_tag_types=3)
        arr = np.array([tags])
        ev.update({"pred": arr, "gold": arr,
                   "length": np.array([len(tags)])})
        return ev._pred, _oracle_chunks(np.array(tags), len(tags), 3)

    # B-0 I-1 (type switch inside) → two chunks
    assert spans([0, 3]) == (2, {(0, 0, 0), (1, 1, 1)})
    # B-0 B-0 → two chunks
    assert spans([0, 0]) == (2, {(0, 0, 0), (1, 1, 0)})
    # I-2 at t=0 begins → one chunk
    assert spans([5, 5]) == (1, {(0, 1, 2)})


@pytest.mark.parametrize("seed", range(5))
def test_chunk_vectorized_matches_oracle(seed):
    """Vectorized batch extraction == per-token oracle on random tag soup."""
    rng = np.random.RandomState(seed)
    num_types = 3
    B, T = 8, 17
    pred = rng.randint(0, 2 * num_types + 1, size=(B, T))
    gold = rng.randint(0, 2 * num_types + 1, size=(B, T))
    lengths = rng.randint(0, T + 1, size=(B,))
    ev = ChunkEvaluator(num_tag_types=num_types)
    ev.update({"pred": pred, "gold": gold, "length": lengths})
    correct = npred = ngold = 0
    for b in range(B):
        pc = _oracle_chunks(pred[b], lengths[b], num_types)
        gc = _oracle_chunks(gold[b], lengths[b], num_types)
        correct += len(pc & gc)
        npred += len(pc)
        ngold += len(gc)
    assert (ev._correct, ev._pred, ev._gold) == (correct, npred, ngold)


# --------------------------------------------------------- ctc_edit_distance

def _lev_oracle(a, b):
    """Plain O(nm) scalar-loop Levenshtein with the reference's backtrace
    tie-break (match > sub > del > ins), returning (sub, del, ins)."""
    n, m = len(a), len(b)
    D = [[0] * (m + 1) for _ in range(n + 1)]
    for i in range(n + 1):
        D[i][0] = i
    for j in range(m + 1):
        D[0][j] = j
    for i in range(1, n + 1):
        for j in range(1, m + 1):
            c = 0 if a[i - 1] == b[j - 1] else 1
            D[i][j] = min(D[i - 1][j] + 1, D[i][j - 1] + 1,
                          D[i - 1][j - 1] + c)
    i, j, sub, dele, ins = n, m, 0, 0, 0
    while i and j:
        if D[i][j] == D[i - 1][j - 1] and a[i - 1] == b[j - 1]:
            i, j = i - 1, j - 1
        elif D[i][j] == D[i - 1][j - 1] + 1:
            sub, i, j = sub + 1, i - 1, j - 1
        elif D[i][j] == D[i - 1][j] + 1:
            dele, i = dele + 1, i - 1
        else:
            ins, j = ins + 1, j - 1
    return sub, dele + i, ins + j


def _collapse_oracle(path, blank):
    out, prev = [], -1
    for lab in path:
        if lab != blank and (not out or lab != out[-1] or prev == blank):
            out.append(int(lab))
        prev = lab
    return out


@pytest.mark.parametrize("seed", range(3))
def test_ctc_error_vs_oracle(seed):
    from paddle_tpu.train.evaluators import CtcErrorEvaluator
    rng = np.random.RandomState(seed)
    B, T, C, L = 6, 20, 5, 8          # blank = C-1 = 4
    ev = CtcErrorEvaluator()
    paths = rng.randint(0, C, size=(B, T))
    lengths = rng.randint(3, T + 1, size=B)
    labels = np.full((B, L), -1)
    label_lens = rng.randint(0, L + 1, size=B)
    for b in range(B):
        labels[b, :label_lens[b]] = rng.randint(0, C - 1, size=label_lens[b])
    ev.update({"path": paths, "length": lengths, "label": labels,
               "label_length": label_lens, "blank": C - 1})

    score = sub_t = del_t = ins_t = 0.0
    seq_err = 0
    for b in range(B):
        hyp = _collapse_oracle(paths[b, :lengths[b]], C - 1)
        gold = list(labels[b, :label_lens[b]])
        if not gold:
            sub, dele, ins = 0, 0, len(hyp)
        elif not hyp:
            sub, dele, ins = 0, len(gold), 0
        else:
            sub, dele, ins = _lev_oracle(gold, hyp)
        ml = max(1, len(gold), len(hyp))
        score += (sub + dele + ins) / ml
        sub_t += sub / ml
        del_t += dele / ml
        ins_t += ins / ml
        seq_err += int(sub + dele + ins != 0)
    res = ev.result()
    assert abs(res["error"] - score / B) < 1e-9
    assert abs(res["substitution_error"] - sub_t / B) < 1e-9
    assert abs(res["deletion_error"] - del_t / B) < 1e-9
    assert abs(res["insertion_error"] - ins_t / B) < 1e-9
    assert abs(res["sequence_error"] - seq_err / B) < 1e-9


def test_ctc_backtrace_tie_break_checks_chars():
    """A zero-cost diagonal tie with unequal chars must NOT count as a
    match (ADVICE r2): gold [0,2,1,0,2] vs hyp [2,1,0,1,1] is distance 3 =
    1 sub + 1 del + 1 ins; the unchecked-diagonal backtrace reports 3 subs."""
    from paddle_tpu.train.evaluators import _backtrace_counts
    gold = np.array([0, 2, 1, 0, 2])
    hyp = np.array([2, 1, 0, 1, 1])
    D = _edit_matrix_oracle(gold, hyp)
    assert _backtrace_counts(D, 5, 5, gold, hyp) == (1, 1, 1)


def _edit_matrix_oracle(a, b):
    n, m = len(a), len(b)
    D = np.zeros((n + 1, m + 1), np.int32)
    D[:, 0] = np.arange(n + 1)
    D[0, :] = np.arange(m + 1)
    for i in range(1, n + 1):
        for j in range(1, m + 1):
            c = 0 if a[i - 1] == b[j - 1] else 1
            D[i, j] = min(D[i - 1, j] + 1, D[i, j - 1] + 1, D[i - 1, j - 1] + c)
    return D


def test_ctc_perfect_prediction_zero_error():
    from paddle_tpu.train.evaluators import CtcErrorEvaluator
    ev = CtcErrorEvaluator()
    # path "a _ b b _ c" decodes to [a, b, c] with blank=3
    paths = np.array([[0, 3, 1, 1, 3, 2]])
    labels = np.array([[0, 1, 2, -1]])
    ev.update({"path": paths, "length": np.array([6]), "label": labels,
               "label_length": np.array([3]), "blank": 3})
    res = ev.result()
    assert res["error"] == 0.0 and res["sequence_error"] == 0.0


def test_ctc_repeat_needs_blank():
    from paddle_tpu.train.evaluators import CtcErrorEvaluator
    ev = CtcErrorEvaluator()
    # "a a" collapses to [a]; gold is [a, a] -> one deletion / maxlen 2
    ev.update({"path": np.array([[0, 0]]), "length": np.array([2]),
               "label": np.array([[0, 0]]), "label_length": np.array([2]),
               "blank": 3})
    res = ev.result()
    assert abs(res["error"] - 0.5) < 1e-9
    assert abs(res["deletion_error"] - 0.5) < 1e-9


# ----------------------------------------------------------- sums & printers

def test_sum_and_column_sum():
    from paddle_tpu.train.evaluators import SumEvaluator, ColumnSumEvaluator
    s = SumEvaluator()
    s.update({"sum": 6.0, "count": 3.0})
    s.update({"sum": 4.0, "count": 2.0})
    assert abs(s.result()["sum"] - 2.0) < 1e-9
    c = ColumnSumEvaluator()
    c.update({"sum": np.array([2.0, 4.0]), "count": 2.0})
    c.update({"sum": np.array([4.0, 2.0]), "count": 2.0})
    assert np.allclose(c.result()["column_sum"], [1.5, 1.5])


def test_printers_log_without_scoring():
    from paddle_tpu.train.evaluators import (MaxIdPrinter, SequenceTextPrinter,
                                             ValuePrinter)
    lines = []
    vp = ValuePrinter(sink=lines.append)
    vp.update({"mean": np.float32(0.5), "abs_max": np.float32(2.0),
               "shape": np.array([2, 3])})
    mp = MaxIdPrinter(sink=lines.append)
    mp.update({"ids": np.array([1, 0, 2])})
    tp = SequenceTextPrinter(vocab={0: "<s>", 1: "hi", 2: "</s>"},
                             sink=lines.append)
    tp.update({"ids": np.array([[0, 1, 2]]), "length": np.array([3])})
    assert len(lines) == 3
    assert "mean=" in lines[0] and "ids=[1, 0, 2]" in lines[1]
    assert "<s> hi </s>" in lines[2]
    assert vp.result() == {} and mp.result() == {}


def test_sum_evaluator_fractional_weights():
    from paddle_tpu.train.evaluators import SumEvaluator
    s = SumEvaluator()
    s.update({"sum": 2.0, "count": 0.5})      # two samples of weight 0.25
    assert abs(s.result()["sum"] - 4.0) < 1e-9


def test_ctc_evaluator_blank_convention():
    """blank defaults to 0 (this package's ctc_loss convention); blank=-1
    selects the reference's last-class blank."""
    import jax.numpy as jnp
    from paddle_tpu.train.evaluators import CtcErrorEvaluator
    # logits whose argmax path is [0, 1, 0, 2] over C=4 classes
    out = np.full((1, 4, 4), -5.0, np.float32)
    for t, c in enumerate([0, 1, 0, 2]):
        out[0, t, c] = 5.0
    batch = {"length": np.array([4]), "label": np.array([[1, 2, -1]]),
             "label_length": np.array([2])}
    ev0 = CtcErrorEvaluator()                      # blank=0
    stats = {k: np.asarray(v) for k, v in
             ev0.batch_stats(jnp.asarray(out), batch).items()}
    assert int(stats["blank"]) == 0
    ev0.update(stats)
    assert ev0.result()["error"] == 0.0            # path collapses to [1, 2]
    ev_last = CtcErrorEvaluator(blank=-1)
    stats = {k: np.asarray(v) for k, v in
             ev_last.batch_stats(jnp.asarray(out), batch).items()}
    assert int(stats["blank"]) == 3
