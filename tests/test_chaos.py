"""Partition-tolerant membership + network chaos tests (ISSUE 20).

The fleet's safety story across an unreliable network: epoch leases
stamped on every frame, fence-by-epoch on declare-dead (a zombie on an
unreachable host rejects its revoked epoch child-side), flap damping
(K consecutive stale observations before the death verdict), jittered
capped backoff on dials and retransmits, partition-heal re-admission,
and disagg→colocated degradation when every prefill replica is gone.
The chaos plane itself — per-link delay/throttle/drop/partition/flap at
the frame seam — is drilled for determinism (two same-seed runs draw
identical verdict ledgers) and frame coherence (a dropped message takes
its declared blobs with it; the stream never desynchronizes).

Protocol-level tests drive ``serve_loop`` with fakes over pipes (no jax
child); the split-brain drill pays for real socket children because the
asymmetric-partition evidence chain (timeout → transport_down → stale
heartbeat → fence → readmit) only exists end-to-end.
"""

import collections
import os
import random
import socket
import tempfile
import threading

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.parallel import multihost
from paddle_tpu.serve import transport as tp
from paddle_tpu.serve.chaos import (ChaosFrameReader, ChaosWriter,
                                    LinkChaos, NetworkChaos)
from paddle_tpu.serve.replica_proc import (EventBuffer, SettableClock,
                                           serve_loop)
from paddle_tpu.serve.router import FleetRouter

V, W = 64, 24
DT, HB = 0.1, 0.25


# ---------------------------------------------------------------------------
# LinkChaos: validation, windows, flap schedule
# ---------------------------------------------------------------------------

def test_link_chaos_validation_and_down_windows():
    with pytest.raises(ValueError):
        LinkChaos(drop_p=1.5)
    with pytest.raises(ValueError):
        LinkChaos(flap=(0.0, 0.1))
    with pytest.raises(ValueError):
        LinkChaos(flap=(1.0, 2.0))          # down > period
    with pytest.raises(ValueError):
        LinkChaos(direction="sideways")
    with pytest.raises(ValueError):
        LinkChaos(partitions=[(0.0, 1.0, "up")])
    # asymmetric partition: recv cut, send up — and partitions WIN over
    # the flap in the reason (the window is the deliberate drill)
    lc = LinkChaos(partitions=[(1.0, 2.0, "recv")],
                   flap=(1.0, 0.25, 0.0))
    assert lc.down_reason("recv", 1.5) == "partition"
    assert lc.down_reason("send", 1.5) == "flap" or \
        lc.down_reason("send", 1.5) is None
    assert lc.down_reason("recv", 2.0) in ("flap", None)  # half-open end
    # flap square wave: down for the first down_s of every period
    fl = LinkChaos(flap=(1.0, 0.25, 2.0))
    assert fl.down_reason("send", 1.9) is None      # before start
    assert fl.down_reason("send", 2.1) == "flap"
    assert fl.down_reason("send", 2.5) is None
    assert fl.down_reason("send", 3.2) == "flap"    # next period
    # direction gating: a send-only profile never impairs recv
    so = LinkChaos(drop_p=1.0, direction="send")
    assert so.applies("send") and not so.applies("recv")
    d = LinkChaos().describe()
    assert d["drop_p"] == 0.0 and d["flap"] is None


def test_chaos_verdicts_deterministic_across_two_runs():
    """The determinism satellite: two same-seed planes fed the same
    clocked message sequence produce the identical verdict ledger —
    delay samples, drop draws, flap windows and all."""
    def run(seed):
        clock = SettableClock()
        ch = NetworkChaos(seed, links={
            1: LinkChaos(delay_s=(0.001, 0.004), jitter_s=0.001,
                         drop_p=0.3, bandwidth_bps=8e6,
                         flap=(0.5, 0.1)),
            2: LinkChaos(drop_p=0.5, direction="recv")},
            max_sleep_s=0.0)                # account, never sleep
        ch.bind(clock)
        verdicts = []
        for i in range(200):
            clock.set(i * 0.01)
            verdicts.append(ch.verdict(1, "send", 100 + i))
            verdicts.append(ch.verdict(1, "recv", 50))
            verdicts.append(ch.verdict(2, "recv", 200))
        return verdicts, ch.stats()
    v1, s1 = run(7)
    v2, s2 = run(7)
    assert v1 == v2 and s1 == s2
    assert s1["frames_dropped"] > 0 and s1["frames_delayed"] > 0
    assert "flap" in s1["drop_reasons"] and "drop" in s1["drop_reasons"]
    # the throttle is visible: link 1's sends pay bytes*8/bps on top of
    # the sampled delay, so its ledger carries real injected seconds
    assert s1["per_link"][1]["delay_s"] > 0.0
    v3, s3 = run(8)
    assert s3 != s1                         # a different seed diverges
    # link 2 is recv-only: its send direction never dropped anything
    assert s1["per_link"][2]["dropped_send"] == 0
    assert s1["per_link"][2]["dropped_recv"] > 0
    # describe() is full provenance: config + the verdict ledger
    ch = NetworkChaos(3, default=LinkChaos(drop_p=0.1))
    d = ch.describe()
    assert d["seed"] == 3 and d["default"]["drop_p"] == 0.1
    assert d["stats"]["frames_dropped"] == 0


# ---------------------------------------------------------------------------
# the frame seams: drops are frame-coherent, blobs inherit verdicts
# ---------------------------------------------------------------------------

def test_chaos_reader_drop_consumes_blobs_and_keeps_sync():
    """A dropped message takes its declared binary payloads down with
    it: the reader consumes them off the wire (accounted as dropped
    bytes) and the NEXT message is delivered intact — chaos loses
    exchanges, never desynchronizes the stream."""
    a, b = socket.socketpair()
    try:
        clock = SettableClock()
        ch = NetworkChaos(0, links={
            5: LinkChaos(partitions=[(0.0, 1.0, "recv")])},
            max_sleep_s=0.0)
        ch.bind(clock)
        reader = ChaosFrameReader(b, ch, 5)
        w = tp.SocketWriter(a)
        blob = b"\x42" * 64
        tp.write_frame(w, {"seq": 1, "nblobs": 1, "op": "adopt"})
        tp.write_binary_frame(w, blob)
        tp.write_frame(w, {"seq": 2, "op": "tick"})
        # inside the window: seq 1 dropped WITH its blob, seq 2 is the
        # next coherent frame... but the window drops it too; advance
        # the clock between reads to watch the partition lift
        clock.set(0.5)
        with pytest.raises(tp.TransportTimeout):
            reader.read_frame(timeout_s=0.2)
        dropped_before = ch.bytes_dropped
        assert ch.frames_dropped == 2       # seq 1 and seq 2
        assert dropped_before > len(blob)   # the blob bytes counted too
        clock.set(1.5)                      # healed
        tp.write_frame(w, {"seq": 3, "nblobs": 1, "op": "adopt"})
        tp.write_binary_frame(w, blob)
        got = reader.read_frame(timeout_s=1.0)
        assert got == {"seq": 3, "nblobs": 1, "op": "adopt"}
        # the delivered message's blob passes through untouched
        assert reader.read_frame(timeout_s=1.0,
                                 allow_binary=True) == blob
        assert ch.bytes_dropped == dropped_before
    finally:
        a.close(), b.close()


def test_chaos_writer_blob_inherits_message_verdict():
    """Outbound seam: a JSON frame draws the verdict; the binary frames
    riding behind it inherit it — dropped whole or delivered whole."""
    a, b = socket.socketpair()
    try:
        clock = SettableClock()
        ch = NetworkChaos(0, links={
            3: LinkChaos(partitions=[(0.0, 1.0, "send")])},
            max_sleep_s=0.0)
        ch.bind(clock)
        cw = ChaosWriter(tp.SocketWriter(a), ch, 3)
        blob = b"\x77" * 128
        clock.set(0.5)                      # partitioned: both vanish
        tp.write_frame(cw, {"seq": 1, "nblobs": 1})
        tp.write_binary_frame(cw, blob)
        clock.set(2.0)                      # healed: both delivered
        tp.write_frame(cw, {"seq": 2, "nblobs": 1})
        tp.write_binary_frame(cw, blob)
        reader = tp.SocketFrameReader(b)
        assert reader.read_frame(timeout_s=1.0) == {"seq": 2,
                                                    "nblobs": 1}
        assert reader.read_frame(timeout_s=1.0,
                                 allow_binary=True) == blob
        assert ch.frames_dropped == 1       # one message verdict
        assert ch.bytes_dropped > len(blob)  # its blob went with it
        # a profile-less link is returned UNWRAPPED — the chaos-off
        # fleet runs the stock classes, byte-identical
        w = tp.SocketWriter(a)
        assert ch.wrap_writer(9, w) is w
    finally:
        a.close(), b.close()


# ---------------------------------------------------------------------------
# backoff satellites: seeded jitter on retransmits and dials
# ---------------------------------------------------------------------------

def _pipe_pair():
    r, w = os.pipe()
    return os.fdopen(r, "rb"), os.fdopen(w, "wb")


def test_retransmit_backoff_capped_jittered_seeded():
    def run(seed):
        slept = []
        c2p_r, _w = _pipe_pair()
        _r, p2c_w = _pipe_pair()
        tr = tp.ReplicaTransport(c2p_r, p2c_w, timeout_s=0.02,
                                 max_attempts=4, backoff_seed=seed,
                                 sleep=slept.append)
        with pytest.raises(tp.TransportTimeout):
            tr.request("tick", now=0.0, tick=0)
        stats = (tr.backoffs, tr.backoff_s)
        tr.close()
        return slept, stats
    slept, (n, total) = run(11)
    # attempts 2..4 back off before resending: uniform(0, base * 2^k)
    # capped — never a sleep beyond the cap, growth bounded per attempt
    assert len(slept) == 3 and n == len([s for s in slept if s > 0])
    for k, s in enumerate(slept):
        assert 0.0 <= s <= min(0.25, 0.02 * (2.0 ** k))
    assert total == pytest.approx(sum(slept))
    # seeded: the same link draws the same delays every run
    assert run(11)[0] == slept
    assert run(12)[0] != slept


def test_connect_dial_backoff_jittered_and_injectable():
    def run(seed):
        slept = []
        with pytest.raises(tp.TransportClosed):
            tp.connect("127.0.0.1", 1, timeout_s=0.25,
                       retry_interval_s=0.05,
                       rng=random.Random(seed), sleep=slept.append)
        return slept
    slept = run(5)
    assert len(slept) >= 2
    for k, s in enumerate(slept):
        assert 0.0 <= s <= min(0.5, 0.05 * (2.0 ** min(k, 10)))
    # injectable rng == replay of the jitter draws; the attempt COUNT
    # is real-deadline-bounded, so compare the common prefix
    again = run(5)
    n = min(len(slept), len(again))
    assert n >= 2 and again[:n] == slept[:n]


# ---------------------------------------------------------------------------
# flap damping: K stale observations before the death verdict
# ---------------------------------------------------------------------------

class _RWorker:
    def __init__(self, rid):
        self.replica_id = rid
        self.state = "live"


def test_flap_damping_one_late_beat_is_not_death(tmp_path):
    root = str(tmp_path)
    w0, w1 = _RWorker(0), _RWorker(1)
    router = FleetRouter([w0, w1], root, heartbeat_timeout_s=HB,
                         death_confirmations=2)
    for rid in (0, 1):
        multihost.write_heartbeat(root, rid, now=0.0)
    assert router.refresh_health(0.1) == []
    # replica 1's beat arrives one observation late: first stale look
    # starts the streak but must NOT declare death (K=2)
    multihost.write_heartbeat(root, 0, now=1.0)
    assert router.refresh_health(1.0) == []
    assert w1.state == "live" and router._stale_streak[1] == 1
    # the late beat lands before the second look: flap absorbed
    multihost.write_heartbeat(root, 1, now=1.1)
    multihost.write_heartbeat(root, 0, now=1.2)
    assert router.refresh_health(1.2) == []
    assert router.false_deaths_averted == 1
    assert 1 not in router._stale_streak
    # sustained staleness IS death — at exactly the K'th observation
    multihost.write_heartbeat(root, 0, now=2.0)
    assert router.refresh_health(2.0) == []          # streak 1
    multihost.write_heartbeat(root, 0, now=2.1)
    newly = router.refresh_health(2.1)               # streak 2 → dead
    assert [w.replica_id for w in newly] == [1]
    assert w1.state == "dead"
    # K=1 restores the old single-observation verdict
    r1 = FleetRouter([_RWorker(7)], root, heartbeat_timeout_s=HB,
                     death_confirmations=1)
    multihost.write_heartbeat(root, 7, now=0.0)
    assert [w.replica_id for w in r1.refresh_health(5.0)] == [7]


# ---------------------------------------------------------------------------
# child-side lease protocol over pipes (fakes, no jax child)
# ---------------------------------------------------------------------------

class _FakeCache:
    free_blocks = 7
    num_blocks = 8
    block_size = 4
    prefix_hit_blocks = 0
    cow_forks = 0


class _FakeEngine:
    max_slots = 2
    ticks = 0
    tokens_generated = 0
    cache = _FakeCache()
    context_width = W

    def free_slots(self):
        return [0, 1]

    def compile_counts(self):
        return {"prefill": 1, "tick": 1}

    def evict(self, slot):
        pass


class _FakeScheduler:
    def __init__(self):
        self.steps = 0
        self.est_tick_s = 0.1
        self.queue, self.running, self.prefilling = [], {}, {}
        self.completed = []
        self.submitted = []

    def step(self):
        self.steps += 1
        return False

    def submit(self, prompt, max_new_tokens, **kw):
        self.submitted.append((list(prompt), max_new_tokens, kw))

    def pending_new_tokens(self):
        return 0

    def load_report(self):
        return {"pending_new_tokens": 0, "running": 0, "queued": 0,
                "prefilling": 0}


def _loopback(tmpdir, **kw):
    c2p_r, c2p_w = _pipe_pair()
    p2c_r, p2c_w = _pipe_pair()
    eng, sched = _FakeEngine(), _FakeScheduler()
    t = threading.Thread(
        target=serve_loop, args=(p2c_r, c2p_w),
        kwargs=dict(engine=eng, sched=sched, buf=EventBuffer(),
                    clock=SettableClock(), root=tmpdir, replica_id=0,
                    **kw),
        daemon=True)
    t.start()
    tr = tp.ReplicaTransport(c2p_r, p2c_w, timeout_s=1.0)
    return tr, eng, sched, t


def test_child_lease_fence_reject_and_readmit(tmp_path):
    tr, eng, sched, t = _loopback(str(tmp_path))
    # hello is the grant: the child adopts epoch 1 and stamps replies
    hello = tr.request("hello", now=0.0, epoch=1)
    assert hello["ok"] and hello["epoch"] == 1
    assert tr.request("tick", now=0.1, tick=0, epoch=1)["ok"]
    assert sched.steps == 1
    # the revocation notice: the child self-fences and adopts epoch 2
    r = tr.request("fence", now=0.2, epoch=2)
    assert r["ok"] and r["fenced"]
    assert r["fence"]["reason"] == "revoked" and r["fence"]["epoch"] == 1
    assert r["fence"]["tokens_at_fence"] == 0
    # THE fence: the zombie's op with the revoked epoch never executes
    z = tr.request("tick", now=0.3, tick=1, epoch=1)
    assert z["ok"] is False and z["error"] == "stale_epoch"
    assert z["epoch"] == 2 and sched.steps == 1
    # even the CURRENT epoch is refused while fenced — only a readmit
    # (strictly newer lease) re-opens the membership
    f = tr.request("tick", now=0.4, tick=2, epoch=2)
    assert f["ok"] is False and f["error"] == "fenced"
    stale = tr.request("readmit", now=0.5, epoch=2)
    assert stale["ok"] is False and stale["error"] == "stale_epoch"
    ok = tr.request("readmit", now=0.6, epoch=3)
    assert ok["ok"] and ok["epoch"] == 3
    assert ok["tokens_while_fenced"] == 0
    assert ok["stale_epoch_rejects"] == 1
    assert ok["fence"]["reason"] == "revoked"
    # re-admitted: ops under the fresh lease execute again
    assert tr.request("tick", now=0.7, tick=3, epoch=3)["ok"]
    assert sched.steps == 2
    st = tr.request("stats", now=0.8, epoch=3)
    assert st["fenced"] is False and st["stale_epoch_rejects"] == 1
    tr.request("stop")
    t.join(timeout=5.0)
    tr.close()


def test_child_superseded_and_lease_expiry(tmp_path):
    tr, eng, sched, t = _loopback(str(tmp_path), lease_timeout_s=5.0)
    assert tr.request("hello", now=0.0, epoch=1)["ok"]
    assert tr.request("tick", now=0.1, tick=0, epoch=1)["ok"]
    # a NEWER epoch on a plain op means someone else holds this
    # replica's lease now: fence, don't race the successor
    sup = tr.request("tick", now=0.2, tick=1, epoch=4)
    assert sup["ok"] is False and sup["error"] == "fenced"
    assert sched.steps == 1
    ok = tr.request("readmit", now=0.3, epoch=5)
    assert ok["ok"] and ok["fence"]["reason"] == "superseded"
    # lease expiry: a contact gap beyond lease_timeout_s makes the
    # child fence UNILATERALLY — its lease may have been reissued
    # during a partition it cannot see
    assert tr.request("tick", now=0.4, tick=2, epoch=5)["ok"]
    exp = tr.request("tick", now=99.0, tick=3, epoch=5)
    assert exp["ok"] is False and exp["error"] == "fenced"
    assert sched.steps == 2
    re = tr.request("readmit", now=99.1, epoch=6)
    assert re["ok"] and re["fence"]["reason"] == "lease-expired"
    tr.request("stop")
    t.join(timeout=5.0)
    tr.close()


# ---------------------------------------------------------------------------
# fleet-level drills (real model): degradation + split brain
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_and_vars():
    from paddle_tpu.models import TransformerLM
    model = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                          ffn_hidden=64, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))
    return model, vs


def _greedy_oracle(model, vs, prompt, n_new):
    fwd = jax.jit(lambda v, i: model.apply(v, i))
    seq, out = list(prompt), []
    for _ in range(n_new):
        pad = np.zeros((1, W), np.int32)
        pad[0, :len(seq)] = seq
        logits = fwd(vs, jnp.asarray(pad))
        tok = int(np.argmax(np.asarray(logits[0, len(seq) - 1])))
        out.append(tok)
        seq.append(tok)
    return out


def test_chaos_requires_socket_mode(model_and_vars):
    from paddle_tpu.serve import ServingFleet, SimClock
    model, vs = model_and_vars
    with pytest.raises(ValueError, match="socket"):
        ServingFleet.from_model(
            model, vs, 1, engine_kwargs=dict(max_slots=2, block_size=4),
            clock=SimClock(), chaos=NetworkChaos(0))


def test_disagg_degradation_to_colocated_and_release(model_and_vars,
                                                     nprng):
    """Partition degradation, in-process: the only prefill replica
    dies; after the grace window the fleet degrades — decode replicas
    serve colocated prefill (identical tokens, just no handoff) — and
    a prefill replica rejoining releases it immediately."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    from paddle_tpu.serve import ServingFleet, SimClock
    from paddle_tpu.train import FaultSchedule
    model, vs = model_and_vars
    mem = InMemorySink()
    fleet = ServingFleet.from_model(
        model, vs, 2, engine_kwargs=dict(max_slots=2, block_size=4,
                                         num_blocks=24),
        roles=["prefill", "decode"], clock=SimClock(),
        heartbeat_timeout_s=HB, est_tick_s=DT,
        telemetry=Telemetry(sinks=[mem]),
        faults=FaultSchedule(kill_replica_at_tick=(1, 0)),
        root=tempfile.mkdtemp(prefix="paddle_tpu_chaos_degrade_"))
    jobs = [(list(nprng.randint(1, V, 4)), 5) for _ in range(4)]
    frs = [fleet.submit(p, n) for p, n in jobs]
    for _ in range(400):
        if not fleet.outstanding():
            break
        fleet.tick()
        fleet.clock.advance(DT)
    assert not fleet.outstanding()
    # all requests completed COLOCATED on the decode replica, token-
    # identical to the oracle — degraded means slower, never stuck
    assert fleet.degraded and fleet.degradations == 1
    for (p, n), fr in zip(jobs, frs):
        assert fr.finish_reason == "length"
        assert fr.replica == 1
        assert fr.tokens == _greedy_oracle(model, vs, p, n)
    assert fleet.stats()["membership"]["degraded"] is True
    degs = [r for r in mem.records if r.get("kind") == "degrade"]
    assert [d["event"] for d in degs] == ["engaged"]
    # a prefill replica rejoining releases the degradation at once,
    # and fresh requests hand off again
    fleet.spawn_replica("prefill")
    fleet.tick()
    fleet.clock.advance(DT)
    assert not fleet.degraded and fleet.degrade_releases == 1
    before = fleet.handoff_count
    p2 = list(nprng.randint(1, V, 4))
    fr2 = fleet.submit(p2, 4)
    for _ in range(200):
        if not fleet.outstanding():
            break
        fleet.tick()
        fleet.clock.advance(DT)
    assert fr2.finish_reason == "length"
    assert fr2.tokens == _greedy_oracle(model, vs, p2, 4)
    assert fleet.handoff_count == before + 1
    degs = [r for r in mem.records if r.get("kind") == "degrade"]
    assert [d["event"] for d in degs] == ["engaged", "released"]
    summ_membership = fleet.stats()["membership"]
    assert summ_membership["degradations"] == 1
    assert summ_membership["degrade_releases"] == 1


def test_split_brain_asymmetric_partition_fence_and_readmit(
        model_and_vars, nprng):
    """THE acceptance drill: an asymmetric partition (child hears us,
    we cannot hear it) manufactures a false death. The fenced zombie
    must contribute ZERO tokens under its revoked epoch — asserted
    child-side via a crafted stale-epoch op AND the readmit report —
    every rid keeps exactly one terminal record with oracle tokens, and
    the healed replica rejoins under a fresh lease."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    from paddle_tpu.serve import ServingFleet, SimClock
    model, vs = model_and_vars
    # the window opens at 0.25 — before ANY job can finish (8 new
    # tokens ≈ 8+ DT-ticks) — so the partitioned replica is guaranteed
    # to hold in-flight rids when it is declared dead
    heal_at = 2.0
    chaos = NetworkChaos(13, links={
        1: LinkChaos(partitions=[(0.25, heal_at, "recv")])})
    mem = InMemorySink()
    fleet = ServingFleet.from_model(
        model, vs, 2, engine_kwargs=dict(max_slots=2, block_size=4),
        replica_mode="socket", chaos=chaos, clock=SimClock(),
        heartbeat_timeout_s=HB, est_tick_s=DT,
        # warm children: a COLD first tick compiles for seconds, which
        # would trip the deliberately-short transport timeout on the
        # HEALTHY link and fence both replicas — the drill needs the
        # timeout to mean "partition", not "compiling"
        warmup=True,
        transport_timeout_s=0.75, readmit_grace_s=100.0,
        telemetry=Telemetry(sinks=[mem]),
        root=tempfile.mkdtemp(prefix="paddle_tpu_chaos_split_"))
    try:
        # the chaos-off link runs the STOCK seam classes (byte-identity
        # doctrine); the impaired link runs the chaos ones
        w0, w1 = fleet.workers
        assert type(w0.transport._reader) is tp.SocketFrameReader
        assert type(w1.transport._reader) is ChaosFrameReader
        assert w0.lease_epoch == 1 and w1.lease_epoch == 2
        jobs = [(list(nprng.randint(1, V, int(nprng.randint(2, 6)))), 8)
                for _ in range(6)]
        frs = [fleet.submit(p, n) for p, n in jobs]
        old_ep = w1.lease_epoch
        poke = None
        for _ in range(400):
            if poke is None and fleet.clock() >= heal_at \
                    and w1.state == "dead":
                # the partition healed but the parent hasn't readmitted
                # yet: poke the zombie DIRECTLY with its revoked epoch —
                # the child itself must refuse it
                poke = w1.transport.request(
                    "tick", now=fleet.clock(), tick=-1, epoch=old_ep,
                    max_attempts=1, timeout_s=1.0)
            if not fleet.outstanding() and fleet.readmitted >= 1:
                break
            fleet.tick()
            fleet.clock.advance(DT)
        assert not fleet.outstanding()
        # the false death happened and was fenced by epoch, not by kill
        assert fleet.fences == 1 and not w1.killed
        assert w1.transport.proc.poll() is None      # the zombie lives
        assert poke is not None
        assert poke["ok"] is False and poke["error"] == "stale_epoch"
        assert poke["epoch"] > old_ep
        # partition heal → re-admission under a fresh lease
        assert fleet.readmitted == 1 and w1.state == "live"
        assert w1.lease_epoch > old_ep
        info = w1.readmit_info
        assert info["tokens_while_fenced"] == 0
        assert info["stale_epoch_rejects"] >= 1
        # every request: exactly one terminal record, oracle tokens
        by_rid = collections.defaultdict(list)
        for r in mem.records:
            if r.get("kind") == "request":
                by_rid[r["rid"]].append(r)
        for (p, n), fr in zip(jobs, frs):
            assert fr.finish_reason == "length"
            assert fr.tokens == _greedy_oracle(model, vs, p, n)
            terminal = [r for r in by_rid[fr.rid]
                        if r["finish_reason"] != "retried"]
            assert len(terminal) == 1, (fr.rid, by_rid[fr.rid])
        # the in-flight work on the partitioned replica was resubmitted
        assert any(fr.retries > 0 for fr in frs)
        # membership + chaos evidence in stats and the record stream
        st = fleet.stats()
        assert st["membership"]["fences"] == 1
        assert st["membership"]["readmitted"] == 1
        assert st["chaos"]["frames_dropped"] > 0
        assert st["chaos"]["drop_reasons"].get("partition", 0) > 0
        fences = [r for r in mem.records if r.get("kind") == "fence"]
        assert any(r.get("reason") == "declared-dead"
                   and r.get("epoch") == old_ep for r in fences)
        readmits = [r for r in mem.records
                    if r.get("kind") == "replica"
                    and r.get("event") == "readmitted"]
        assert len(readmits) == 1
        assert readmits[0]["tokens_while_fenced"] == 0
    finally:
        fleet.shutdown()
