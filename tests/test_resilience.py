"""Elastic fault tolerance (ISSUE 10): the kill-anywhere property, the
checkpoint fallback chain, preemption, heartbeats, and the
zero-overhead-when-off pin.

The acceptance contract: for a seeded fault-schedule sweep (crash
before/during/after save, preemption mid-pass, corrupt latest pass,
stager producer error) the SUPERVISED run completes and its final params
are BIT-EQUAL (f32) to the uninterrupted run — recovery is not
"approximately resumes", it is the same training trajectory. And with
``faults=None``, no supervisor, no heartbeat, the Trainer is the exact
pre-PR hot loop (dispatch count, fences, params)."""

import glob
import os
import signal
import time

import numpy as np
import jax
import pytest

from paddle_tpu import data, optim
from paddle_tpu.models import MnistMLP
from paddle_tpu.nn import costs
from paddle_tpu.parallel import multihost
from paddle_tpu.train import (FaultSchedule, InjectedCrash, Preempted,
                              SupervisorGaveUp, Trainer, checkpoint as ckpt,
                              faults as faults_lib, resilience,
                              run_resilient)

BS, N_BATCHES = 8, 16


def make_batches(n=N_BATCHES, bs=BS, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.rand(bs, 784).astype(np.float32),
             "label": rng.randint(0, 10, (bs,)).astype(np.int32)}
            for _ in range(n)]


BATCHES = make_batches()


def reader():
    return iter(BATCHES)


def make_trainer(faults=None, **kw):
    tr = Trainer(
        model=MnistMLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3), faults=faults, **kw)
    tr.init(jax.random.PRNGKey(0), BATCHES[0])
    return tr


def params_of(state):
    return jax.tree_util.tree_leaves(jax.device_get(state.params))


def assert_params_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def baseline(tmp_path, num_passes=2, saving_period=4, **kw):
    tr = make_trainer(**kw)
    tr.train(reader, num_passes=num_passes,
             checkpoint_dir=str(tmp_path / "baseline"),
             saving_period=saving_period, log_period=0)
    return params_of(tr.train_state)


# ---------------------------------------------------------------------------
# the kill-anywhere sweep (acceptance criterion)
# ---------------------------------------------------------------------------

# (name, FaultSchedule kwargs, extra Trainer kwargs). All with
# steps_per_call=2 over 16 batches x 2 passes (M=1: 16 optimizer steps
# per pass; saving_period=4: boundary saves at batches 4/8/12/16, plus
# the pass-end save — save indices 0..4 in pass 0, 5..9 in pass 1).
SWEEP = [
    # crash before ANY save lands (step 1, first group): resume finds no
    # checkpoint and replays from scratch
    ("crash_before_save", dict(crash_at_step=1), {}),
    # crash right after the batch-4 boundary save: resume mid-pass
    ("crash_after_save", dict(crash_at_step=5), {}),
    # crash INSIDE the save path (the write never lands): transient I/O,
    # retry resumes from the previous checkpoint
    ("crash_during_save", dict(fail_save_at=1), {}),
    # the latest landed checkpoint (pass-0 end, save idx 4) is corrupted,
    # then a crash early in pass 1: resume quarantines the poisoned pass
    # and falls back (here: to scratch — bench's --faults-child covers
    # the fall-back-one-PASS case with 3 passes)
    ("corrupt_latest_pass",
     dict(corrupt_checkpoint_file=4, crash_at_step=18), {}),
    # preemption notice mid-pass: graceful stop -> quiesced checkpoint ->
    # distinct status -> a second supervised run resumes
    ("preempt_mid_pass", dict(preempt_at_step=5), {}),
    # the stager thread dies staging a group (producer-error propagation
    # through the host pipeline): supervisor retries with resume
    ("stager_error", dict(stager_error_at_group=4),
     {"pipeline_depth": 2}),
]


@pytest.mark.parametrize("name,fs_kw,tr_kw",
                         SWEEP, ids=[s[0] for s in SWEEP])
def test_kill_anywhere_bit_equal(tmp_path, name, fs_kw, tr_kw):
    p0 = baseline(tmp_path, steps_per_call=2, **tr_kw)
    ck = str(tmp_path / "supervised")
    # ONE schedule instance across attempts: the one-shot disarm is what
    # makes the injected fault transient
    fs = FaultSchedule(**fs_kw)
    res = run_resilient(
        lambda: make_trainer(faults=fs, steps_per_call=2, **tr_kw),
        reader, checkpoint_dir=ck, num_passes=2, saving_period=4,
        log_period=0, backoff_s=0.001)
    if res.status == "preempted":
        # the preempt checkpoint recorded the quiesced mid-pass position
        assert res.preempted is not None
        it = ckpt.load_checkpoint(ck)["iter"]
        assert int(it["preempted"]) == 1 and int(it["completed"]) == 0
        res = run_resilient(
            lambda: make_trainer(steps_per_call=2, **tr_kw),
            reader, checkpoint_dir=ck, num_passes=2, saving_period=4,
            log_period=0, backoff_s=0.001)
    assert res.status == "completed", (name, res)
    assert fs.fired, name                 # the fault really fired
    assert_params_equal(p0, params_of(res.state))
    if name == "corrupt_latest_pass":
        assert res.fallbacks, res
        assert glob.glob(os.path.join(ck, "*.corrupt*"))


def test_supervisor_gives_up_on_deterministic_failure(tmp_path):
    """A failure recurring at the same step (fresh schedule each attempt,
    no checkpoint to skip past it) is deterministic — give up loud with
    the attempts ledger, don't burn the restart budget."""
    with pytest.raises(SupervisorGaveUp, match="recurred"):
        run_resilient(
            lambda: make_trainer(faults=FaultSchedule(crash_at_step=2),
                                 steps_per_call=2),
            reader, checkpoint_dir=str(tmp_path / "ck"), num_passes=1,
            log_period=0, backoff_s=0.001, same_step_limit=3,
            max_restarts=10)


def test_supervisor_restart_budget(tmp_path):
    """Distinct failures past max_restarts also give up (chained)."""
    calls = {"n": 0}

    def flaky_reader():
        calls["n"] += 1
        raise OSError(f"flaky transport #{calls['n']}")

    with pytest.raises(SupervisorGaveUp, match="budget"):
        run_resilient(
            lambda: make_trainer(steps_per_call=2), flaky_reader,
            checkpoint_dir=str(tmp_path / "ck"), num_passes=1,
            log_period=0, backoff_s=0.001, max_restarts=2,
            same_step_limit=99)


def test_nan_is_fatal_not_retried(tmp_path):
    """nan_check's FloatingPointError re-raises immediately: a restart
    replays the same batches into the same NaN."""
    bad = [{"x": np.full((BS, 784), np.nan, np.float32),
            "label": np.zeros((BS,), np.int32)}]
    attempts = {"n": 0}

    def make():
        attempts["n"] += 1
        return make_trainer(nan_check=True)

    with pytest.raises(FloatingPointError):
        run_resilient(make, lambda: iter(bad),
                      checkpoint_dir=str(tmp_path / "ck"), num_passes=1,
                      log_period=0, backoff_s=0.001)
    assert attempts["n"] == 1             # no retry


# ---------------------------------------------------------------------------
# zero-overhead-when-off pin (PR-2/4/6 style)
# ---------------------------------------------------------------------------

def _count_dispatches(tr):
    calls = {"n": 0}
    orig = tr._dispatch_fused

    def counting(stacked, rng, **kw):
        calls["n"] += 1
        return orig(stacked, rng, **kw)

    tr._dispatch_fused = counting
    tr.train(reader, num_passes=1, log_period=0)
    return calls["n"]


def test_faults_off_zero_overhead(monkeypatch):
    """faults=None, no supervisor, no heartbeat: same dispatch count,
    zero fences, bit-identical params vs an attached-but-empty schedule
    — the injection plane costs nothing when disarmed and nothing is
    traced into the step either way."""
    fences = {"n": 0}
    orig_fence = jax.block_until_ready

    def counting_fence(x):
        fences["n"] += 1
        return orig_fence(x)

    monkeypatch.setattr(jax, "block_until_ready", counting_fence)

    tr_off = make_trainer(steps_per_call=2)
    n_off = _count_dispatches(tr_off)
    assert fences["n"] == 0

    tr_empty = make_trainer(faults=FaultSchedule(), steps_per_call=2)
    n_empty = _count_dispatches(tr_empty)
    assert n_empty == n_off
    assert fences["n"] == 0               # still no fence either way
    assert_params_equal(params_of(tr_off.train_state),
                        params_of(tr_empty.train_state))


def test_fault_points_are_one_shot():
    fs = FaultSchedule(crash_at_step=2)
    with pytest.raises(InjectedCrash):
        fs.maybe_crash_step(2)
    fs.maybe_crash_step(2)                # disarmed: no raise
    assert fs.fired == [("crash_at_step", 2)]
    fs2 = FaultSchedule(preempt_at_step=4)
    assert fs2.should_preempt(4) is True
    assert fs2.should_preempt(4) is False


# ---------------------------------------------------------------------------
# checkpoint fallback chain + resume seams
# ---------------------------------------------------------------------------

def _save(root, pass_id, val):
    ckpt.save_checkpoint(str(root), pass_id,
                         {"params": {"w": np.full((4,), float(val))}})


def test_load_latest_valid_quarantines_and_falls_back(tmp_path, caplog):
    _save(tmp_path, 0, 1.0)
    _save(tmp_path, 1, 2.0)
    corrupted = faults_lib.corrupt_one_file(
        os.path.join(str(tmp_path), "pass-00001"))
    assert corrupted is not None
    with caplog.at_level("WARNING"):
        out = ckpt.load_latest_valid(str(tmp_path))
    assert out["pass_id"] == 0
    np.testing.assert_allclose(out["params"]["w"], np.ones((4,)))
    # quarantined, never deleted: the bytes are still on disk
    q = os.path.join(str(tmp_path), "pass-00001.corrupt")
    assert out["_quarantined"] == [q]
    assert os.path.isdir(q)
    assert not os.path.exists(os.path.join(str(tmp_path), "pass-00001"))
    assert any("quarantined" in r.message for r in caplog.records)


def test_fallback_prefers_readable_sibling_of_same_pass(tmp_path):
    """A corrupt live dir with a complete .old crash leftover falls back
    WITHIN the pass first: quarantine the live dir, read the .old."""
    root = str(tmp_path / "root")
    side = str(tmp_path / "side")
    ckpt._write_pass_dir(root, 0, {"params": {"w": np.full((2,), 2.0)}})
    # a crash leftover from the v1 save era (built aside: the live
    # writer's swap garbage-collects true .old siblings on success)
    ckpt._write_pass_dir(side, 0, {"params": {"w": np.full((2,), 1.0)}})
    os.rename(os.path.join(side, "pass-00000"),
              os.path.join(root, "pass-00000.old"))
    faults_lib.corrupt_one_file(os.path.join(root, "pass-00000"))
    out = ckpt.load_latest_valid(root)
    assert out["pass_id"] == 0
    np.testing.assert_allclose(out["params"]["w"], np.full((2,), 1.0))
    assert os.path.isdir(os.path.join(root, "pass-00000.corrupt"))


def test_all_corrupt_raises_with_ledger(tmp_path):
    _save(tmp_path, 0, 1.0)
    faults_lib.corrupt_one_file(os.path.join(str(tmp_path), "pass-00000"))
    with pytest.raises(FileNotFoundError) as ei:
        ckpt.load_latest_valid(str(tmp_path))
    assert len(ei.value.quarantined) == 1
    assert os.path.isdir(ei.value.quarantined[0])


def test_corrupt_dirs_invisible_to_latest_resolve_and_gc(tmp_path):
    root = str(tmp_path)
    for i in range(3):
        _save(tmp_path, i, float(i))
    q = ckpt.quarantine_pass_dir(os.path.join(root, "pass-00002"))
    assert ckpt.latest_pass(root) == 1
    assert ckpt._base_pass_id(os.path.basename(q)) is None
    ckpt._gc(root, keep_last=1)
    left = sorted(d for d in os.listdir(root) if d.startswith("pass-"))
    # retention pruned pass-0, kept pass-1, left the quarantine alone
    assert left == ["pass-00001", "pass-00002.corrupt"]


def test_quarantine_name_collisions_get_suffixes(tmp_path):
    root = str(tmp_path)
    for _ in range(2):
        _save(tmp_path, 0, 1.0)
        ckpt.quarantine_pass_dir(os.path.join(root, "pass-00000"))
    names = sorted(os.listdir(root))
    assert names == ["pass-00000.corrupt", "pass-00000.corrupt2"]


def test_resume_starts_fresh_when_nothing_readable(tmp_path, caplog):
    """Trainer(resume=True) over an all-corrupt checkpoint dir warns and
    trains from scratch — bit-equal to a clean run — instead of dying."""
    p0 = baseline(tmp_path, num_passes=1, saving_period=None)
    ck = str(tmp_path / "ck")
    tr = make_trainer()
    tr.train(reader, num_passes=1, checkpoint_dir=ck, log_period=0)
    faults_lib.corrupt_one_file(os.path.join(ck, "pass-00000"))
    tr2 = make_trainer()
    with caplog.at_level("WARNING"):
        tr2.train(reader, num_passes=1, checkpoint_dir=ck, resume=True,
                  log_period=0)
    assert any("starting from scratch" in r.message for r in caplog.records)
    assert tr2.last_quarantined                  # the ledger survived
    assert_params_equal(p0, params_of(tr2.train_state))


def test_vanished_dir_mid_read_rescans_not_raises(tmp_path, monkeypatch):
    """Multi-reader race: another host quarantines (renames away) the
    pass dir between our latest_pass probe and the load — we must
    RE-SCAN and converge on the same fallback pass, not die or restart
    from scratch on the other host's rename."""
    import shutil
    _save(tmp_path, 0, 1.0)
    _save(tmp_path, 1, 2.0)
    real_load = ckpt.load_checkpoint
    raced = {"n": 0}

    def racing_load(root, pass_id=None, **kw):
        if pass_id == 1 and raced["n"] == 0:
            raced["n"] += 1
            # the "other host" moved it away mid-read
            shutil.move(os.path.join(root, "pass-00001"),
                        os.path.join(root, "pass-00001.corrupt"))
            raise FileNotFoundError("vanished under concurrent rename")
        return real_load(root, pass_id, **kw)

    monkeypatch.setattr(ckpt, "load_checkpoint", racing_load)
    out = ckpt.load_latest_valid(str(tmp_path))
    assert out["pass_id"] == 0 and raced["n"] == 1
    assert out["_quarantined"] == []          # we didn't quarantine it


def test_stop_request_scoped_to_one_train_call(tmp_path):
    """A consumed (or stale) stop request must not instantly re-preempt
    the next train() on the same instance — zero-forward-progress loop
    otherwise."""
    ck = str(tmp_path / "ck")
    tr = make_trainer()

    def handler(e):
        from paddle_tpu.train import events as ev
        if isinstance(e, ev.EndIteration) and e.batch_id == 1 \
                and e.pass_id == 0:
            tr.request_stop("once")

    with pytest.raises(Preempted):
        tr.train(reader, num_passes=1, checkpoint_dir=ck, log_period=0,
                 event_handler=handler)
    # same instance, no new request: must run to completion
    state = tr.train(reader, num_passes=1, checkpoint_dir=ck,
                     resume=True, log_period=0)
    assert state is tr.train_state
    it = ckpt.load_checkpoint(ck)["iter"]
    assert int(it["completed"]) == 1


def test_preempt_checkpoint_carries_batch_crc(tmp_path):
    """The preempt save records the last consumed batch's fingerprint —
    the resume-time nondeterministic-reader check guards the elastic
    path like every saving_period save."""
    ck = str(tmp_path / "ck")
    tr = make_trainer(steps_per_call=2)
    fs = FaultSchedule(preempt_at_step=5)
    tr.faults = fs
    with pytest.raises(Preempted) as ei:
        tr.train(reader, num_passes=1, checkpoint_dir=ck, log_period=0)
    it = ckpt.load_checkpoint(ck)["iter"]
    nb = ei.value.next_batch
    from paddle_tpu.train.trainer import _batch_fingerprint
    assert int(it["batch_crc"]) == _batch_fingerprint(BATCHES[nb - 1])


def test_detect_dead_hosts_uses_mtime_in_production(tmp_path):
    """Production staleness is the heartbeat FILE's mtime (one clock
    pair per reader), so a live host with a skewed wall clock is never
    declared dead — and a genuinely stale file is, whatever its payload
    claims."""
    root = str(tmp_path)
    # host 0: beating now, but its wall clock is an hour behind
    multihost.write_heartbeat(root, host_id=0, now=time.time() - 3600)
    # host 1: payload claims "now", but the file is actually old
    p = multihost.write_heartbeat(root, host_id=1, now=time.time())
    os.utime(p, (time.time() - 3600, time.time() - 3600))
    assert multihost.detect_dead_hosts(root, timeout_s=60.0) == [1]


def test_explicit_pass_id_restore_stays_strict(tmp_path):
    """restore(dir, pass_id) keeps the hard-raise contract — only the
    latest-valid path (pass_id=None) quarantines."""
    ck = str(tmp_path / "ck")
    tr = make_trainer()
    tr.train(reader, num_passes=1, checkpoint_dir=ck, log_period=0)
    faults_lib.corrupt_one_file(os.path.join(ck, "pass-00000"))
    with pytest.raises(ckpt.CorruptCheckpointError):
        make_trainer().restore(ck, 0)
    assert os.path.isdir(os.path.join(ck, "pass-00000"))  # untouched


def test_resolve_crash_leftovers_under_quarantine(tmp_path):
    """The kill-between-the-two-renames leftovers (.tmp newer than
    .old) still resolve after the newer one is quarantined."""
    root = str(tmp_path / "root")
    side = str(tmp_path / "side")
    os.makedirs(root)
    ckpt._write_pass_dir(side, 0, {"params": {"w": np.full((2,), 1.0)}})
    os.rename(os.path.join(side, "pass-00000"),
              os.path.join(root, "pass-00000.old"))
    ckpt._write_pass_dir(side, 0, {"params": {"w": np.full((2,), 2.0)}})
    os.rename(os.path.join(side, "pass-00000"),
              os.path.join(root, "pass-00000.tmp"))
    # live missing: .tmp (newer) resolves first
    assert ckpt._resolve_pass_dir(root, 0).endswith(".tmp")
    faults_lib.corrupt_one_file(os.path.join(root, "pass-00000.tmp"))
    out = ckpt.load_latest_valid(root)
    np.testing.assert_allclose(out["params"]["w"], np.full((2,), 1.0))
    assert os.path.isdir(os.path.join(root, "pass-00000.tmp.corrupt"))


# ---------------------------------------------------------------------------
# preemption: request_stop / SIGTERM
# ---------------------------------------------------------------------------

def test_request_stop_quiesces_and_resume_is_bit_equal(tmp_path):
    """A stop requested mid-pass (the signal handler's effect) drains,
    writes a quiesced mid-pass checkpoint, raises Preempted with the
    exact iterator position — and the resumed run is bit-equal."""
    p0 = baseline(tmp_path, num_passes=2, saving_period=None)
    ck = str(tmp_path / "ck")
    tr = make_trainer()

    def handler(e):
        from paddle_tpu.train import events as ev
        if isinstance(e, ev.EndIteration) and e.batch_id == 2 \
                and e.pass_id == 0:
            tr.request_stop("test")

    with pytest.raises(Preempted) as ei:
        tr.train(reader, num_passes=2, checkpoint_dir=ck, log_period=0,
                 event_handler=handler)
    assert ei.value.pass_id == 0 and ei.value.next_batch == 3
    it = ckpt.load_checkpoint(ck)["iter"]
    assert int(it["next_batch"]) == 3 and int(it["preempted"]) == 1
    tr2 = make_trainer()
    tr2.train(reader, num_passes=2, checkpoint_dir=ck, resume=True,
              log_period=0)
    assert_params_equal(p0, params_of(tr2.train_state))


def test_sigterm_handler_requests_stop():
    tr = make_trainer()
    restore = resilience.install_preemption_handler(tr)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5
        while tr._stop_requested is None and time.time() < deadline:
            time.sleep(0.01)              # the delivery checkpoint
        assert tr._stop_requested is not None
        assert "signal" in tr._stop_requested
    finally:
        restore()


# ---------------------------------------------------------------------------
# producer error landing on a checkpoint drain boundary (resume seam)
# ---------------------------------------------------------------------------

def test_buffered_producer_error_at_drain_boundary(tmp_path):
    """A data.buffered fill-thread failure that lands exactly on the
    saving_period drain boundary surfaces promptly (no hang, stager
    closed), the boundary checkpoint is intact, and the supervised retry
    finishes bit-equal."""
    p0 = baseline(tmp_path, steps_per_call=2, pipeline_depth=2)
    failures = {"n": 0}

    def flaky_source():
        for i, b in enumerate(BATCHES):
            if i == 8 and failures["n"] == 0:     # exactly the boundary
                failures["n"] += 1
                raise ValueError("injected producer failure at boundary")
            yield b

    flaky_reader = data.buffered(lambda: flaky_source(), size=2)
    ck = str(tmp_path / "ck")
    res = run_resilient(
        lambda: make_trainer(steps_per_call=2, pipeline_depth=2),
        flaky_reader, checkpoint_dir=ck, num_passes=2, saving_period=4,
        log_period=0, backoff_s=0.001)
    assert res.status == "completed" and res.restarts == 1
    assert failures["n"] == 1
    assert_params_equal(p0, params_of(res.state))


# ---------------------------------------------------------------------------
# heartbeats, dead-host detection, reformed-mesh restart
# ---------------------------------------------------------------------------

def test_heartbeat_write_read_detect(tmp_path):
    root = str(tmp_path)
    multihost.write_heartbeat(root, host_id=0, seq=1, now=100.0)
    multihost.write_heartbeat(root, host_id=1, seq=1, now=100.0)
    multihost.write_heartbeat(root, host_id=2, seq=1, now=40.0)  # stale
    beats = multihost.read_heartbeats(root)
    assert sorted(beats) == [0, 1, 2]
    assert beats[0]["pid"] == os.getpid() and beats[0]["seq"] == 1
    # host 2 is stale; host 3 never joined (only dead when expected)
    assert multihost.detect_dead_hosts(root, timeout_s=30.0,
                                       now=110.0) == [2]
    assert multihost.detect_dead_hosts(
        root, timeout_s=30.0, expected_hosts=range(4), now=110.0) == [2, 3]


def test_reform_plan_ranks_and_resharded_reader(tmp_path):
    root = str(tmp_path)
    for h, ts in ((0, 100.0), (1, 40.0), (2, 100.0), (3, 100.0)):
        multihost.write_heartbeat(root, host_id=h, now=ts)
    plan = multihost.plan_reform(root, timeout_s=30.0, now=110.0)
    assert plan.dead == [1]
    assert plan.survivors == [0, 2, 3]
    assert plan.rank_of == {0: 0, 2: 1, 3: 2}     # contiguous re-rank
    # disjoint coverage over the SURVIVING count
    items = list(range(9))
    shards = [list(plan.sharded_reader(lambda: iter(items), host_id=h)())
              for h in plan.survivors]
    assert sorted(x for s in shards for x in s) == items
    with pytest.raises(ValueError, match="not a survivor"):
        plan.sharded_reader(lambda: iter(items), host_id=1)


def test_reform_builds_mesh_over_survivors(tmp_path):
    root = str(tmp_path)
    multihost.write_heartbeat(root, host_id=0)      # fresh (real clock)
    mesh, plan = multihost.reform(root, timeout_s=30.0,
                                  expected_hosts=[0, 1])
    assert plan.dead == [1] and plan.host_count == 1
    # single-process test topology: the mesh spans the live local devices
    assert mesh.devices.size == jax.device_count()


def test_heartbeat_thread_beats_and_stops(tmp_path):
    hb = multihost.HostHeartbeat(str(tmp_path), interval_s=0.01, host_id=7)
    with hb:
        deadline = time.time() + 5
        path = multihost.heartbeat_path(str(tmp_path), 7)
        while time.time() < deadline:
            beats = multihost.read_heartbeats(str(tmp_path))
            if beats.get(7, {}).get("seq", 0) >= 2:
                break
            time.sleep(0.01)
    assert os.path.exists(path)
    assert multihost.read_heartbeats(str(tmp_path))[7]["seq"] >= 2
    assert hb._thread is None             # joined


def test_supervisor_keeps_heartbeat_fresh(tmp_path):
    ck = str(tmp_path / "ck")
    res = run_resilient(
        lambda: make_trainer(steps_per_call=2), reader,
        checkpoint_dir=ck, num_passes=1, log_period=0, backoff_s=0.001,
        heartbeat_interval_s=0.05)
    assert res.status == "completed"
    beats = multihost.read_heartbeats(ck)
    assert beats and beats[0]["seq"] >= 1


# ---------------------------------------------------------------------------
# restart/fallback telemetry records
# ---------------------------------------------------------------------------

def test_restart_emits_telemetry_record(tmp_path):
    from paddle_tpu.obs import InMemorySink, Telemetry
    mem = InMemorySink()
    tel = Telemetry(sinks=[mem], health=False, memory=False)
    fs = FaultSchedule(crash_at_step=5)
    res = run_resilient(
        lambda: make_trainer(faults=fs, steps_per_call=2, telemetry=tel),
        reader, checkpoint_dir=str(tmp_path / "ck"), num_passes=1,
        saving_period=4, log_period=0, backoff_s=0.001)
    assert res.status == "completed"
    restarts = mem.by_kind("restart")
    assert len(restarts) == 1
    assert restarts[0]["failure"] == "crash" and restarts[0]["step"] == 5
    assert restarts[0]["backoff_s"] >= 0
