"""Packed-sequence representation tests (Argument/SequenceToBatch successor)."""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core import SeqBatch, pack_sequences, unpack_sequences
from paddle_tpu.core.sequence import length_mask, segment_mask, positions_from_segments


def test_from_list_and_mask():
    seqs = [np.arange(3), np.arange(5), np.arange(1)]
    sb = SeqBatch.from_list(seqs)
    assert sb.data.shape == (3, 5)
    np.testing.assert_array_equal(np.asarray(sb.lengths), [3, 5, 1])
    m = np.asarray(sb.mask())
    assert m.sum() == 9
    assert m[0, 2] == 1 and m[0, 3] == 0


def test_pack_roundtrip(nprng):
    seqs = [nprng.randint(0, 100, size=(L,)) for L in [7, 3, 5, 2, 9, 1, 4]]
    data, seg, pos = pack_sequences(seqs, row_len=10)
    # total tokens preserved
    assert (seg > 0).sum() == sum(len(s) for s in seqs)
    # waste bounded: rows * row_len < 2x tokens for this mix
    rec = unpack_sequences(data, seg)
    got = sorted(tuple(r.tolist()) for r in rec)
    want = sorted(tuple(s.tolist()) for s in seqs)
    assert got == want


def test_positions_reset_per_segment():
    seg = np.array([[1, 1, 1, 2, 2, 0]])
    pos = positions_from_segments(seg)
    np.testing.assert_array_equal(pos[0], [0, 1, 2, 0, 1, 0])


def test_segment_attn_mask_blocks_cross_segment():
    seg = jnp.array([[1, 1, 2, 0]])
    m = segment_mask(seg, seg)
    assert m[0, 0, 1] == 1   # same segment
    assert m[0, 0, 2] == 0   # cross segment
    assert m[0, 0, 3] == 0   # pad
    sb = SeqBatch(jnp.zeros((1, 4)), jnp.array([3]), segment_ids=seg)
    am = sb.attn_mask(causal=True)
    assert am[0, 1, 0] == 1 and am[0, 0, 1] == 0


def test_length_mask():
    m = np.asarray(length_mask(jnp.array([2, 0, 4]), 4))
    assert m.tolist() == [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 1]]
