"""Reader combinator + dataset tests (analog of v2/reader/tests and
gserver/tests/test_PyDataProvider2)."""

import numpy as np
import pytest

from paddle_tpu import data
from paddle_tpu.data import datasets


def counting_reader(n):
    def reader():
        yield from range(n)
    return reader


def test_map_shuffle_batch():
    r = data.map_readers(lambda x: x * 2, counting_reader(10))
    assert sorted(r()) == [0, 2, 4, 6, 8, 10, 12, 14, 16, 18]
    s = data.shuffle(counting_reader(10), 5, seed=0)
    out = list(s())
    assert sorted(out) == list(range(10))
    assert out != list(range(10))  # actually shuffled
    # deterministic given a seed
    assert list(s()) == out


def test_batched_fixed_shapes():
    r = data.batched(counting_reader(10), 4)
    batches = list(r())
    assert len(batches) == 2  # drop_last
    assert batches[0].shape == (4,)
    r2 = data.batched(counting_reader(10), 4, drop_last=False)
    assert [b.shape[0] for b in r2()] == [4, 4, 2]


def test_batched_tuple_and_dict():
    def r():
        for i in range(4):
            yield {"x": np.ones((3,)) * i, "label": i}
    b = next(iter(data.batched(r, 2)()))
    assert b["x"].shape == (2, 3)
    assert b["label"].tolist() == [0, 1]

    def rt():
        for i in range(4):
            yield np.ones(2) * i, i
    bt = next(iter(data.batched(rt, 2)()))
    assert bt[0].shape == (2, 2) and bt[1].tolist() == [0, 1]


def test_compose_chain_firstn():
    c = data.compose(counting_reader(3), counting_reader(3))
    assert list(c()) == [(0, 0), (1, 1), (2, 2)]
    ch = data.chain(counting_reader(2), counting_reader(2))
    assert list(ch()) == [0, 1, 0, 1]
    assert list(data.firstn(counting_reader(100), 3)()) == [0, 1, 2]


def test_buffered_propagates_errors():
    def bad():
        yield 1
        raise ValueError("boom")
    r = data.buffered(bad, 2)
    with pytest.raises(ValueError, match="boom"):
        list(r())


def test_sharded_partition():
    shards = [list(data.sharded(counting_reader(10), 3, i)()) for i in range(3)]
    assert sorted(sum(shards, [])) == list(range(10))
    assert shards[0] == [0, 3, 6, 9]


def test_mnist_synthetic_separable():
    r = datasets.mnist("train", synthetic_n=64)
    assert r.is_synthetic
    samples = list(r())
    assert len(samples) == 64
    img, label = samples[0]
    assert img.shape == (28, 28, 1) and 0 <= label < 10
    # deterministic across constructions
    r2 = datasets.mnist("train", synthetic_n=64)
    img2, label2 = next(iter(r2()))
    np.testing.assert_array_equal(img, img2)


def test_other_synthetic_datasets():
    src, tgt = next(iter(datasets.synthetic_nmt(n=4)()))
    assert src.min() >= 3 and tgt.min() >= 3
    toks, tags = next(iter(datasets.synthetic_tagging(n=4)()))
    assert len(toks) == len(tags)
    ids, label = next(iter(datasets.synthetic_ctr(n=4)()))
    assert ids.shape == (8,) and label in (0, 1)
    feats, price = next(iter(datasets.uci_housing()()))
    assert feats.shape == (13,) and price.shape == (1,)
