"""Reader combinator + dataset tests (analog of v2/reader/tests and
gserver/tests/test_PyDataProvider2)."""

import numpy as np
import pytest

from paddle_tpu import data
from paddle_tpu.data import datasets


def counting_reader(n):
    def reader():
        yield from range(n)
    return reader


def test_map_shuffle_batch():
    r = data.map_readers(lambda x: x * 2, counting_reader(10))
    assert sorted(r()) == [0, 2, 4, 6, 8, 10, 12, 14, 16, 18]
    s = data.shuffle(counting_reader(10), 5, seed=0)
    out = list(s())
    assert sorted(out) == list(range(10))
    assert out != list(range(10))  # actually shuffled
    # deterministic given a seed
    assert list(s()) == out


def test_batched_fixed_shapes():
    r = data.batched(counting_reader(10), 4)
    batches = list(r())
    assert len(batches) == 2  # drop_last
    assert batches[0].shape == (4,)
    r2 = data.batched(counting_reader(10), 4, drop_last=False)
    assert [b.shape[0] for b in r2()] == [4, 4, 2]


def test_batched_tuple_and_dict():
    def r():
        for i in range(4):
            yield {"x": np.ones((3,)) * i, "label": i}
    b = next(iter(data.batched(r, 2)()))
    assert b["x"].shape == (2, 3)
    assert b["label"].tolist() == [0, 1]

    def rt():
        for i in range(4):
            yield np.ones(2) * i, i
    bt = next(iter(data.batched(rt, 2)()))
    assert bt[0].shape == (2, 2) and bt[1].tolist() == [0, 1]


def test_compose_chain_firstn():
    c = data.compose(counting_reader(3), counting_reader(3))
    assert list(c()) == [(0, 0), (1, 1), (2, 2)]
    ch = data.chain(counting_reader(2), counting_reader(2))
    assert list(ch()) == [0, 1, 0, 1]
    assert list(data.firstn(counting_reader(100), 3)()) == [0, 1, 2]


def test_buffered_propagates_errors():
    def bad():
        yield 1
        raise ValueError("boom")
    r = data.buffered(bad, 2)
    with pytest.raises(ValueError, match="boom"):
        list(r())


def _buffered_fill_threads():
    import threading
    return [t for t in threading.enumerate()
            if t.name == "paddle_tpu.data.buffered.fill"]


def test_buffered_abandoned_consumer_stops_fill_thread():
    """ISSUE 3 satellite: when the consumer abandons the generator early
    (break / firstn / close), the fill thread must terminate instead of
    blocking forever on q.put into the full bounded queue."""
    import time

    def infinite():
        i = 0
        while True:
            yield i
            i += 1

    r = data.buffered(infinite, 2)
    it = r()
    assert [next(it), next(it)] == [0, 1]     # producer now blocked on put
    assert _buffered_fill_threads()
    it.close()                                # generator finally -> stop
    deadline = time.time() + 5.0
    while _buffered_fill_threads() and time.time() < deadline:
        time.sleep(0.02)
    assert not _buffered_fill_threads(), "fill thread leaked after close()"
    # the firstn composition (islice abandons the generator on GC)
    out = list(data.firstn(data.buffered(infinite, 2), 3)())
    assert out == [0, 1, 2]
    import gc
    gc.collect()
    deadline = time.time() + 5.0
    while _buffered_fill_threads() and time.time() < deadline:
        time.sleep(0.02)
    assert not _buffered_fill_threads()


def test_buffered_error_surfaces_before_queue_drains():
    """Producer exceptions surface PROMPTLY: once the producer has died,
    the consumer raises on its next pull even though successfully-produced
    items are still sitting in the queue ahead of the error."""
    def bad():
        yield 1
        raise ValueError("boom")

    r = data.buffered(bad, 8)                 # queue big enough to hold 1
    it = r()
    import time
    deadline = time.time() + 5.0              # let the producer die first
    while _buffered_fill_threads() and time.time() < deadline:
        time.sleep(0.02)
    with pytest.raises(ValueError, match="boom"):
        next(it)                              # item 1 is buffered — skip it


def test_sharded_partition():
    shards = [list(data.sharded(counting_reader(10), 3, i)()) for i in range(3)]
    assert sorted(sum(shards, [])) == list(range(10))
    assert shards[0] == [0, 3, 6, 9]


def test_mnist_synthetic_separable():
    r = datasets.mnist("train", synthetic_n=64)
    assert r.is_synthetic
    samples = list(r())
    assert len(samples) == 64
    img, label = samples[0]
    assert img.shape == (28, 28, 1) and 0 <= label < 10
    # deterministic across constructions
    r2 = datasets.mnist("train", synthetic_n=64)
    img2, label2 = next(iter(r2()))
    np.testing.assert_array_equal(img, img2)


def test_other_synthetic_datasets():
    src, tgt = next(iter(datasets.synthetic_nmt(n=4)()))
    assert src.min() >= 3 and tgt.min() >= 3
    toks, tags = next(iter(datasets.synthetic_tagging(n=4)()))
    assert len(toks) == len(tags)
    ids, label = next(iter(datasets.synthetic_ctr(n=4)()))
    assert ids.shape == (8,) and label in (0, 1)
    feats, price = next(iter(datasets.uci_housing()()))
    assert feats.shape == (13,) and price.shape == (1,)


# ------------------------------------------------------- remaining datasets

def test_new_dataset_loaders_shapes():
    from paddle_tpu.data import datasets as d
    u, m, uf, mg, r = next(iter(d.movielens("train")()))
    assert uf.shape == (4,) and mg.shape == (6,) and 1.0 <= float(r) <= 5.0
    words, pred, labels = next(iter(d.conll05("train")()))
    assert words.shape == labels.shape and 0 <= int(pred) < len(words)
    ctx, nxt = next(iter(d.imikolov("train", ngram=5)()))
    assert ctx.shape == (4,)
    img, boxes, lab = next(iter(d.voc2012("train")()))
    assert img.shape == (96, 96, 3) and boxes.shape == (4, 4)
    assert (lab >= -1).all()
    f, rel = next(iter(d.mq2007("train")()))
    assert f.shape == (8, 16) and set(np.unique(rel)) <= {0, 1, 2}
    im, l = next(iter(d.flowers("train")()))
    assert im.shape == (64, 64, 3)


def test_datasets_deterministic_across_calls():
    from paddle_tpu.data import datasets as d
    a = [x[0] for _, x in zip(range(3), d.imikolov("train")())]
    b = [x[0] for _, x in zip(range(3), d.imikolov("train")())]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_movielens_learnable_signal():
    """A tiny MF model must beat the constant predictor on held-out data —
    proving the synthetic set carries real structure."""
    from paddle_tpu.data import datasets as d
    # dense setting (80 ratings/user) so a rank-6 MF is identifiable
    kw = dict(n_users=100, n_movies=50)
    rows = list(d.movielens("train", n=8000, **kw)())
    users = np.array([r[0] for r in rows])
    movies = np.array([r[1] for r in rows])
    ratings = np.array([r[4] for r in rows], np.float32)
    gm = ratings.mean()
    # tiny rank-6 MF by full-batch GD (the task is an interaction model, so
    # additive baselines can't capture it — MF must)
    rng = np.random.RandomState(0)
    U = rng.normal(0, 0.3, (100, 6)).astype(np.float32)
    M = rng.normal(0, 0.3, (50, 6)).astype(np.float32)
    lr = 0.1
    for _ in range(200):
        err = ratings - (gm + (U[users] * M[movies]).sum(1))
        U2, M2 = U.copy(), M.copy()
        np.add.at(U2, users, lr * err[:, None] * M[movies] / 80)
        np.add.at(M2, movies, lr * err[:, None] * U[users] / 160)
        U, M = U2, M2
    test = list(d.movielens("test", n=1000, **kw)())
    tu = np.array([r[0] for r in test])
    tm = np.array([r[1] for r in test])
    truth = np.array([r[4] for r in test], np.float32)
    pred = gm + (U[tu] * M[tm]).sum(1)
    mse_model = ((pred - truth) ** 2).mean()
    mse_const = ((gm - truth) ** 2).mean()
    assert mse_model < mse_const * 0.5, (mse_model, mse_const)


# ------------------------------------------------------ image preprocessing

def test_image_transforms():
    from paddle_tpu.data import image as im
    rng = np.random.RandomState(0)
    img = rng.uniform(size=(10, 8, 3)).astype(np.float32)
    r = im.resize(img, (5, 4))
    assert r.shape == (5, 4, 3)
    # resize to the same size is the identity
    np.testing.assert_allclose(im.resize(img, (10, 8)), img)
    c = im.center_crop(img, (4, 4))
    assert c.shape == (4, 4, 3)
    np.testing.assert_allclose(c, img[3:7, 2:6])
    rc = im.random_crop(img, (4, 4), np.random.RandomState(1))
    assert rc.shape == (4, 4, 3)
    n = im.normalize(img, mean=[0.5, 0.5, 0.5], std=[2, 2, 2])
    np.testing.assert_allclose(n, (img - 0.5) / 2, rtol=1e-6)
    assert im.to_chw(img).shape == (3, 10, 8)
    np.testing.assert_allclose(im.to_hwc(im.to_chw(img)), img)
    tf = im.train_augment((4, 4), (6, 6), mean=[0, 0, 0], seed=0)
    assert tf(img).shape == (4, 4, 3)
    ev = im.eval_transform((4, 4), (6, 6), mean=[0, 0, 0])
    assert ev(img).shape == (4, 4, 3)


# ----------------------------------------------------------------- recordio

def test_recordio_roundtrip_and_crc(tmp_path):
    from paddle_tpu.data import recordio as rio
    path = str(tmp_path / "data.rec")
    samples = [{"x": np.arange(4, dtype=np.float32) * i,
                "label": np.int32(i % 3)} for i in range(10)]
    n = rio.write_samples(path, samples)
    assert n == 10 and rio.num_records(path) == 10
    got = list(rio.read_samples(path)())
    assert len(got) == 10
    np.testing.assert_allclose(got[3]["x"], samples[3]["x"])
    assert int(got[7]["label"]) == 1

    # corrupt one payload byte -> CRC failure on read
    offs = rio._offsets(path)
    with open(path, "r+b") as f:
        f.seek(offs[5] + 8 + 1)   # past header into payload
        b = f.read(1)
        f.seek(offs[5] + 8 + 1)
        f.write(bytes([b[0] ^ 0xFF]))
    import pytest
    with pytest.raises(IOError, match="crc"):
        list(rio.read_records(path))


def test_recordio_sharding_disjoint_and_complete(tmp_path):
    from paddle_tpu.data import recordio as rio
    path = str(tmp_path / "data.rec")
    rio.write_samples(path, ({"i": np.int32(i)} for i in range(23)))
    seen = []
    for sid in range(4):
        shard = [int(s["i"]) for s in rio.read_samples(path, 4, sid)()]
        assert shard == list(range(sid, 23, 4))
        seen += shard
    assert sorted(seen) == list(range(23))


def test_recordio_feeds_batched_reader(tmp_path):
    from paddle_tpu import data as d
    from paddle_tpu.data import recordio as rio
    path = str(tmp_path / "data.rec")
    rio.write_samples(path, ({"x": np.full(3, i, np.float32),
                              "label": np.int32(i)} for i in range(8)))
    batches = list(d.batched(rio.read_samples(path), 4)())
    assert len(batches) == 2 and batches[0]["x"].shape == (4, 3)


def test_recordio_failed_write_publishes_no_index(tmp_path):
    from paddle_tpu.data import recordio as rio
    path = str(tmp_path / "bad.rec")

    def exploding():
        yield {"x": np.ones(2, np.float32)}
        raise RuntimeError("source died")

    import pytest
    with pytest.raises(RuntimeError):
        rio.write_samples(path, exploding())
    import os
    assert not os.path.exists(path + ".idx")   # incomplete file stays index-less


def test_recordio_rewrite_invalidates_stale_index(tmp_path):
    from paddle_tpu.data import recordio as rio
    path = str(tmp_path / "data.rec")
    rio.write_samples(path, ({"i": np.int32(i)} for i in range(5)))
    assert rio.num_records(path) == 5

    def exploding():
        yield {"i": np.int32(0)}
        raise RuntimeError("die")

    import pytest
    with pytest.raises(RuntimeError):
        rio.write_samples(path, exploding())
    import os
    # the old index must NOT survive to describe the truncated file
    assert not os.path.exists(path + ".idx")
