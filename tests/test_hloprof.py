"""MFU-gap attribution tests (ISSUE 6): the structured HLO analyzer
(scope extraction, dot/elementwise FLOPs vs cost_analysis, while-loop
trip multipliers, collective inventory incl. the legacy aggregate the
scaling projection is pinned to), the attribution report + exposed-
communication estimate through ``Trainer.attribution_report`` on the
8-device test mesh, the attribution-off byte-identical invariant
(PR-2/4 style), and the measured Chrome-trace join (synthetic capture;
graceful static-only degrade)."""

import gzip
import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import optim
from paddle_tpu.models import TransformerLM
from paddle_tpu.nn import costs
from paddle_tpu.obs import (InMemorySink, Telemetry, attribution, hloprof)
from paddle_tpu.train import Trainer

V, T, BS = 64, 16, 8


def _ca_flops(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return float(ca.get("flops"))


def make_fused_trainer(K=2, M=2, telemetry=None):
    return Trainer(
        model=TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                            ffn_hidden=64, max_len=T, remat="dots"),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(
            out.reshape(-1, V), b["y"].reshape(-1)),
        optimizer=optim.adam(1e-3), steps_per_call=K, grad_accum=M,
        telemetry=telemetry)


def make_batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randint(0, V, (BS, T)).astype(np.int32),
             "y": rng.randint(0, V, (BS, T)).astype(np.int32)}
            for _ in range(n)]


# ---------------------------------------------------------------------------
# scope extraction
# ---------------------------------------------------------------------------

def test_scope_of_unwraps_transforms_and_filters_machinery():
    # forward scope under jvp
    scope, bwd = hloprof.scope_of(
        "jit(step)/jit(main)/jvp(block)/attn/dot_general")
    assert scope == ("block", "attn") and bwd is False
    # backward marks via transpose, scope survives
    scope, bwd = hloprof.scope_of(
        "jit(step)/jit(main)/transpose(jvp(block))/ffn/dot_general")
    assert scope == ("block", "ffn") and bwd is True
    # while/body machinery, checkpoint markers, einsum specs, and arg
    # labels are all non-scopes
    scope, bwd = hloprof.scope_of(
        "jit(f)/jit(main)/while/body/transpose(jvp(while))/body/"
        "checkpoint/block_scan/attn/sdpa_xla/bqhd,bkhd->bhqk/dot_general")
    assert scope == ("block_scan", "attn", "sdpa_xla") and bwd is True
    scope, _ = hloprof.scope_of("opt_state.m[\\'transformer_lm\\'][\\'w\\']")
    assert scope == ()
    assert hloprof.scope_of("") == ((), False)


def test_scope_of_wrapper_spanning_slashes():
    """ISSUE 8: a transform wrapper may span SEVERAL scope components —
    ``transpose(jvp(grad_sync/bucket0))`` — and must not be sheared
    apart at its internal slashes (the naive split lost both the inner
    scopes and the backward flag)."""
    scope, bwd = hloprof.scope_of(
        "jit(f)/jit(main)/jit(shmap_body)/"
        "transpose(jvp(grad_sync/bucket0))/psum")
    assert scope == ("grad_sync", "bucket0") and bwd is True
    scope, bwd = hloprof.scope_of(
        "jit(f)/jit(main)/while/body/"
        "transpose(jvp(block_scan/attn/qkv_proj))/dot_general")
    assert scope == ("block_scan", "attn", "qkv_proj") and bwd is True
    # forward multi-component wrapper: scopes recovered, not backward
    scope, bwd = hloprof.scope_of("jvp(embed/pos)/add")
    assert scope == ("embed", "pos") and bwd is False


def test_sched_distance_async_pairs():
    """ISSUE 8 satellite: an async all-reduce start/done pair reports the
    intervening compute ops (fusions/dots) as its scheduling distance;
    sync collectives report None."""
    hlo = "\n".join([
        "ENTRY %main (p0: f32[64]) -> f32[64] {",
        "  %p0 = f32[64]{0} parameter(0)",
        "  %ars = f32[64]{0} all-reduce-start(f32[64]{0} %p0), "
        "replica_groups={{0,1}}, to_apply=%add",
        "  %f1 = f32[64]{0} fusion(f32[64]{0} %p0), kind=kLoop, "
        "calls=%fused_computation",
        "  %d1 = f32[64]{0} dot(f32[64]{0} %f1, f32[64]{0} %f1), "
        "lhs_contracting_dims={}, rhs_contracting_dims={}",
        "  %t1 = f32[64]{0} tuple(f32[64]{0} %d1)",
        "  %ard = f32[64]{0} all-reduce-done(f32[64]{0} %ars)",
        "  %ar2 = f32[64]{0} all-reduce(f32[64]{0} %ard), "
        "replica_groups={{0,1}}, to_apply=%add",
        "  ROOT %r = f32[64]{0} add(f32[64]{0} %ar2, f32[64]{0} %d1)",
        "}",
    ])
    analysis = hloprof.parse_module(hlo)
    inv = hloprof.collective_inventory(analysis, default_group=2)
    by_name = {c.name: c for c in inv}
    assert by_name["ars"].is_async
    # fusion + dot between start and done; the tuple is plumbing
    assert by_name["ars"].sched_distance == 2
    assert by_name["ar2"].sched_distance is None        # sync op
    assert "sched_distance" in by_name["ars"].to_dict()


# ---------------------------------------------------------------------------
# flops + loop multipliers vs XLA's own cost analysis
# ---------------------------------------------------------------------------

def test_dot_flops_match_cost_analysis():
    w = jnp.asarray(np.random.RandomState(0).randn(64, 96), jnp.float32)
    x = jnp.asarray(np.random.RandomState(1).randn(32, 64), jnp.float32)

    def f(x):
        with jax.named_scope("mm"):
            return jnp.sum(x @ w)

    compiled = jax.jit(f).lower(x).compile()
    analysis = hloprof.parse_module(compiled.as_text())
    # the dot itself: 2 * 32*96 * 64
    dot_flops = sum(op.flops for op in analysis.ops if op.opcode == "dot")
    assert dot_flops == 2 * 32 * 96 * 64
    # total (dot + reduce + any elementwise) tracks cost_analysis
    assert analysis.flops_static() == pytest.approx(_ca_flops(compiled),
                                                    rel=0.05)
    # the dot landed in the named scope
    scoped = [op for op in analysis.ops
              if op.opcode == "dot" and op.scope == ("mm",)]
    assert scoped


def test_while_trip_count_multiplies_loop_aware_flops():
    w = jnp.asarray(np.random.RandomState(0).randn(48, 48), jnp.float32)
    x = jnp.asarray(np.random.RandomState(1).randn(8, 48), jnp.float32)

    def scanned(x):
        def body(c, _):
            return c @ w, None
        y, _ = jax.lax.scan(body, x, None, length=5)
        return y

    compiled = jax.jit(scanned).lower(x).compile()
    analysis = hloprof.parse_module(compiled.as_text())
    # cost_analysis counts the body ONCE; so does flops_static
    assert analysis.flops_static() == pytest.approx(_ca_flops(compiled),
                                                    rel=0.05)
    # the analyzer recovers trips=5 and scales the loop-aware total
    assert 5.0 in analysis.trip_counts.values()
    dot_static = sum(op.flops for op in analysis.ops if op.opcode == "dot")
    dot_aware = sum(op.flops * op.multiplier for op in analysis.ops
                    if op.opcode == "dot")
    assert dot_aware == pytest.approx(5 * dot_static)


# ---------------------------------------------------------------------------
# collective inventory on a real dp mesh (conftest: 8 virtual devices)
# ---------------------------------------------------------------------------

_DP_HLO_CACHE = {}


def _dp_grad_step_hlo():
    """Compile a dp-sharded value_and_grad step on the 8-device test mesh
    and return its optimized HLO + param count (memoized — two tests
    read it)."""
    if "hlo" in _DP_HLO_CACHE:
        return _DP_HLO_CACHE["hlo"]
    import paddle_tpu as pt
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = pt.make_mesh({"data": 8})
    w = jax.device_put(
        jnp.asarray(np.random.RandomState(0).randn(64, 64), jnp.float32),
        NamedSharding(mesh, P()))
    x = jax.device_put(jnp.ones((16, 64)),
                       NamedSharding(mesh, P("data", None)))

    def loss(w, x):
        with jax.named_scope("ffn"):
            h = x @ w
        with jax.named_scope("head"):
            return jnp.mean(h * h)

    def step(w, x):
        l, g = jax.value_and_grad(loss)(w, x)
        return l, w - 0.01 * g

    out = jax.jit(step).lower(w, x).compile().as_text(), 64 * 64
    _DP_HLO_CACHE["hlo"] = out
    return out


def test_collective_inventory_dp_allreduce():
    hlo, n_params = _dp_grad_step_hlo()
    analysis = hloprof.parse_module(hlo)
    inv = hloprof.collective_inventory(analysis, default_group=8)
    ars = [c for c in inv if c.kind == "all-reduce"]
    assert ars
    grad_ar = [c for c in ars if c.backward]
    assert grad_ar, "the grad all-reduce must be flagged backward"
    g = grad_ar[0]
    assert g.group_size == 8
    assert g.payload_bytes == n_params * 4          # f32 grads
    # ring factor: 2B(g-1)/g
    assert g.wire_bytes == pytest.approx(2 * g.payload_bytes * 7 / 8)
    assert g.dtypes == ["f32"]


def test_legacy_parse_collectives_matches_structured_inventory():
    """The promoted legacy aggregate and the structured inventory must
    agree on totals (the projection's numbers ride on the legacy one)."""
    hlo, _ = _dp_grad_step_hlo()
    legacy = hloprof.parse_collectives(hlo, 8)
    analysis = hloprof.parse_module(hlo)
    inv = hloprof.collective_inventory(analysis, default_group=8)
    for kind, agg in legacy.items():
        ops = [c for c in inv if c.kind == kind]
        assert len(ops) == agg["ops"]
        assert sum(c.payload_bytes for c in ops) == agg["buffer_bytes"]
        assert sum(c.wire_bytes for c in ops) == pytest.approx(
            agg["wire_bytes_per_device"])


def test_legacy_parse_collectives_variadic_and_iota_groups():
    """Pinned behaviors of the promoted parser: variadic tuple payloads
    sum, iota replica groups parse, 1-device groups drop, '-start' async
    all-gather counts the result half only."""
    hlo = "\n".join([
        "  %ar = (f32[64]{0}, f32[128,3]{1,0}) all-reduce(f32[64]{0} %a, "
        "f32[128,3]{1,0} %b), replica_groups={{0,1,2,3},{4,5,6,7}}, "
        "to_apply=%add",
        "  %deg = f32[8]{0} all-reduce(f32[8]{0} %c), "
        "replica_groups={{0},{1}}, to_apply=%add",
        "  %ags = (f32[4]{0}, f32[16]{0}) all-gather-start(f32[4]{0} %d), "
        "replica_groups=[2,4]<=[8], dimensions={0}",
    ])
    out = hloprof.parse_collectives(hlo, 8)
    ar = out["all-reduce"]
    assert ar["ops"] == 1                      # degenerate group dropped
    assert ar["buffer_bytes"] == (64 + 128 * 3) * 4
    assert ar["group_sizes"] == [4]
    assert ar["wire_bytes_per_device"] == pytest.approx(
        2 * ar["buffer_bytes"] * 3 / 4)
    ag = out["all-gather"]
    assert ag["buffer_bytes"] == 16 * 4        # result half of the start op
    assert ag["group_sizes"] == [4]


def test_scaling_projection_imports_shared_parser():
    """Single source of truth: the experiment must use obs.hloprof's
    parser, not a private copy."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "experiments", "scaling_projection.py")
    src = open(path).read()
    # loaded by file path (hloprof is stdlib-only; the driver must not
    # eagerly initialize jax in the parent process)
    assert 'os.path.join(REPO, "paddle_tpu", "obs", "hloprof.py")' in src
    assert "parse_collectives = _hloprof.parse_collectives" in src
    assert "def parse_collectives" not in src
    assert "_COLL_RE" not in src


# ---------------------------------------------------------------------------
# Trainer.attribution_report on the 8-device mesh
# ---------------------------------------------------------------------------

def test_trainer_attribution_report_fused(tmp_path):
    mem = InMemorySink()
    tr = make_fused_trainer(telemetry=Telemetry(sinks=[mem]))
    batches = make_batches(2 * 2)
    tr.init(jax.random.PRNGKey(0), batches[0])
    with pytest.raises(ValueError, match="4 host batches"):
        tr.attribution_report(batches[:3])
    report = tr.attribution_report(
        batches, profile_dir=_synthetic_capture(tmp_path))
    # >= 4 named scopes with nonzero FLOPs (the acceptance bar)
    named = [k for k, v in report["scope_rollup"].items()
             if v > 0 and k != "(unscoped)"]
    assert len(named) >= 4
    for want in ("embed", "head", "block_scan/attn", "block_scan/ffn"):
        assert want in report["scope_rollup"], report["scope_rollup"]
    # parsed total agrees with cost_analysis within 5%
    assert report["cost_analysis_flops"] and report["flops_static"] > 0
    assert abs(report["flops_vs_cost_analysis_pct"]) <= 5.0
    # collective inventory with the grad all-reduce exposure estimate
    assert report["collectives"]
    gar = report["comm"]["grad_allreduce"]
    assert gar is not None and gar["ops"] >= 1
    assert gar["exposed_ms_if_overlapped"] is not None
    assert gar["wire_bytes_per_device"] > 0
    # roofline rows are ranked and carry the gap fields
    assert report["scopes"][0]["flops"] >= report["scopes"][-1]["flops"]
    for row in report["scopes"]:
        assert row["bound"] in ("compute", "memory", "none")
        assert row["idle_ms"] >= 0
    assert report["mfu_gap_rank"]
    # the kind="attribution" record reached the sink
    assert len(mem.by_kind("attribution")) == 1
    # CPU mesh: bandwidth tables are the DEFAULT_DEVICE what-if, labelled
    assert report["bandwidth_assumed"] is True
    # the synthetic device-lane capture joined the static report
    assert report["measured"]["exposed_comm_ms"] == pytest.approx(3.0)
    # report is JSON-serializable end to end
    json.dumps(report)
    # and the human rendering doesn't crash
    assert "grad all-reduce" in attribution.format_report(report)


def test_trainer_attribution_report_plain_mode():
    tr = Trainer(
        model=TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                            ffn_hidden=64, max_len=T),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(
            out.reshape(-1, V), b["y"].reshape(-1)),
        optimizer=optim.adam(1e-3))
    batches = make_batches(1)
    tr.init(jax.random.PRNGKey(0), batches[0])
    report = tr.attribution_report(batches[0])
    assert report["fused"] is False
    assert abs(report["flops_vs_cost_analysis_pct"]) <= 5.0
    named = [k for k, v in report["scope_rollup"].items()
             if v > 0 and k != "(unscoped)"]
    assert len(named) >= 4


def test_attribution_off_is_byte_identical(monkeypatch):
    """ISSUE 6 acceptance: attribution is pull-based — a trainer that
    never calls attribution_report is byte-identical to before (same
    dispatch count, zero fences), and CALLING it executes nothing (no
    extra dispatch, train_state/host-step untouched, later training
    bit-identical). Same invariant style as PR 2/4."""
    fences = {"n": 0}
    orig_fence = jax.block_until_ready

    def counting_fence(x):
        fences["n"] += 1
        return orig_fence(x)

    monkeypatch.setattr(jax, "block_until_ready", counting_fence)
    batches = make_batches(2 * 2 * 2)

    def run(with_report):
        tr = make_fused_trainer()                  # telemetry off
        tr.init(jax.random.PRNGKey(0), batches[0])
        calls = {"n": 0}
        orig = tr._dispatch_fused

        def counting(stacked, rng, **kw):
            calls["n"] += 1
            return orig(stacked, rng, **kw)

        tr._dispatch_fused = counting
        if with_report:
            rep = tr.attribution_report(batches[:4], emit=False)
            assert rep["flops_static"] > 0
            assert calls["n"] == 0                 # the report dispatches
            assert tr._host_step == 0              # and executes NOTHING
        tr.train(lambda: iter(batches), num_passes=1, log_period=0)
        return calls["n"], jax.device_get(tr.train_state.params)

    n_plain, p_plain = run(False)
    fences_plain = fences["n"]
    n_rep, p_rep = run(True)
    assert fences_plain == 0 and fences["n"] == 0  # no fence either way
    assert n_plain == n_rep                        # same dispatch count
    for a, b in zip(jax.tree_util.tree_leaves(p_plain),
                    jax.tree_util.tree_leaves(p_rep)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# measured path: Chrome-trace device lanes (synthetic capture)
# ---------------------------------------------------------------------------

def _synthetic_capture(tmp_path, device=True):
    """A fake jax.profiler Chrome trace: one device lane with a 10ms
    compute span, a 4ms all-reduce overlapping its last 1ms (3ms
    exposed), plus a host lane that must be ignored."""
    events = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0" if device else "python"}},
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "python host"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 10_000.0,
         "name": "fusion.123"},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 9_000.0, "dur": 4_000.0,
         "name": "all-reduce-start.5"},
        {"ph": "X", "pid": 1, "tid": 3, "ts": 0.0, "dur": 50_000.0,
         "name": "host_stuff"},
    ]
    d = tmp_path / "plugins" / "profile" / "run1"
    os.makedirs(str(d), exist_ok=True)
    with gzip.open(str(d / "host.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def test_parse_profile_trace_measures_exposed_comm(tmp_path):
    out = attribution.parse_profile_trace(_synthetic_capture(tmp_path))
    assert out is not None
    assert out["device_lanes"] == 1
    assert out["device_compute_ms"] == pytest.approx(10.0)
    assert out["device_comm_ms"] == pytest.approx(4.0)
    assert out["exposed_comm_ms"] == pytest.approx(3.0)
    assert out["comm_overlap_frac"] == pytest.approx(0.25)
    assert out["device_wall_ms"] == pytest.approx(13.0)


def test_parse_profile_trace_degrades_gracefully(tmp_path):
    # no capture at all
    assert attribution.parse_profile_trace(str(tmp_path)) is None
    # a capture with no device lanes (CPU): static-only
    path = _synthetic_capture(tmp_path, device=False)
    assert attribution.parse_profile_trace(path) is None
