"""Worker process for the real two-process jax.distributed test.

Launched by ``test_multiprocess.py`` as::

    python tests/_multiproc_worker.py --coordinator localhost:PORT \
        --num-processes 2 --process-id I --ckpt-dir D --out OUT.json

Forces the CPU backend with 4 virtual devices per process BEFORE importing
jax, joins the distributed runtime through the framework's own
``parallel.multihost.initialize``, builds the global 8-device mesh, trains,
and writes its result JSON for the parent to compare against the
single-process oracle.
"""

import argparse
import json
import os
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

    import jax
    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.parallel import multihost

    multihost.initialize(args.coordinator,
                         num_processes=args.num_processes,
                         process_id=args.process_id)
    assert multihost.is_initialized()
    assert jax.process_count() == args.num_processes, jax.process_count()
    assert jax.device_count() == 4 * args.num_processes, jax.device_count()

    mesh = multihost.multihost_mesh()
    assert mesh.devices.size == 4 * args.num_processes

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _multiproc_common import run_training

    result = run_training(mesh, ckpt_dir=args.ckpt_dir)
    result["process_id"] = jax.process_index()
    result["process_count"] = jax.process_count()
    result["local_devices"] = len(jax.local_devices())
    with open(args.out, "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
