"""Parallelism tests on the virtual 8-device CPU mesh — the analog of the
reference's in-process cluster tests (``test_CompareSparse.cpp:64``,
``ParallelNeuralNetwork.h:36``): tensor-parallel training must match
replicated training; ring attention must match dense attention."""

import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import optim, parallel
from paddle_tpu.core.module import Module
from paddle_tpu.nn import costs
from paddle_tpu.train import Trainer


class MLP(Module):
    def __init__(self, hidden=32, classes=8):
        super().__init__()
        self.hidden = nn.Linear(hidden, act="relu", name="hidden")
        self.out = nn.Linear(classes, name="out")

    def forward(self, x, train=False):
        return self.out(self.hidden(x))


def _batch(nprng, n=32, d=16, classes=8):
    return {
        "x": nprng.normal(size=(n, d)).astype(np.float32),
        "label": nprng.randint(0, classes, size=n).astype(np.int32),
    }


MLP_RULES = parallel.ShardingRules([
    ("*/hidden/w", P(None, "model")),     # column parallel
    ("*/hidden/b", P("model")),
    ("*/out/w", P("model", None)),        # row parallel
])


def _train_losses(mesh, param_sharding, batches, rng):
    trainer = Trainer(
        model=MLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.momentum(0.1, 0.9),
        mesh=mesh, param_sharding=param_sharding, donate=False)
    trainer.init(rng, batches[0])
    trainer._build_train_step()
    ts = trainer.train_state
    p, s, o, st = ts.params, ts.state, ts.opt_state, ts.step
    losses = []
    for hb in batches:
        b = trainer._shard(hb)
        p, s, o, st, loss, stats = trainer._train_step(
            p, s, o, st, b, jax.random.PRNGKey(7))
        losses.append(float(loss))
    return losses, p


def test_tensor_parallel_matches_replicated(nprng, rng):
    """data x model mesh with sharded params == pure-DP replicated params
    (same global batches, same rng) — the ParallelNeuralNetwork equivalence."""
    batches = [_batch(nprng) for _ in range(5)]
    mesh_dp = pt.make_mesh({"data": 8})
    mesh_tp = pt.make_mesh({"data": 2, "model": 4})
    losses_dp, p_dp = _train_losses(mesh_dp, None, batches, rng)
    losses_tp, p_tp = _train_losses(mesh_tp, MLP_RULES, batches, rng)
    np.testing.assert_allclose(losses_dp, losses_tp, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_dp),
                    jax.tree_util.tree_leaves(p_tp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_param_sharding_actually_shards(nprng, rng):
    mesh = pt.make_mesh({"data": 2, "model": 4})
    trainer = Trainer(
        model=MLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3),
        mesh=mesh, param_sharding=MLP_RULES, donate=False)
    trainer.init(rng, _batch(nprng))
    root = next(iter(trainer.train_state.params))
    w = trainer.train_state.params[root]["hidden"]["w"]
    spec = w.sharding.spec
    assert tuple(spec) == (None, "model")
    # optimizer state inherited the layout by SPMD propagation
    m_leaves = [x for x in jax.tree_util.tree_leaves(
        trainer.train_state.opt_state) if getattr(x, "ndim", 0) == 2
        and x.shape == w.shape]
    assert m_leaves, "adam should carry param-shaped slots"
    for leaf in m_leaves:
        assert tuple(leaf.sharding.spec) == (None, "model")


def test_sharded_restore_recommits_layout(nprng, rng, tmp_path):
    """save -> restore with param_sharding keeps the tensor-parallel layout
    (params, state, and optimizer slots)."""
    mesh = pt.make_mesh({"data": 2, "model": 4})
    def make():
        return Trainer(
            model=MLP(),
            loss_fn=lambda out, b: costs.softmax_cross_entropy(
                out, b["label"]),
            optimizer=optim.adam(1e-3),
            mesh=mesh, param_sharding=MLP_RULES, donate=False)
    t1 = make()
    t1.init(rng, _batch(nprng))
    t1.save(str(tmp_path), 0)
    t2 = make()
    t2.init(rng, _batch(nprng))          # builds _param_specs
    t2.restore(str(tmp_path), 0)
    root = next(iter(t2.train_state.params))
    w = t2.train_state.params[root]["hidden"]["w"]
    assert tuple(w.sharding.spec) == (None, "model")
    for leaf in jax.tree_util.tree_leaves(t2.train_state.opt_state):
        if getattr(leaf, "shape", None) == w.shape:
            assert tuple(leaf.sharding.spec) == (None, "model")


def test_sharded_init_layout(nprng, rng):
    mesh = pt.make_mesh({"data": 2, "model": 4})
    model = MLP(hidden=64)
    x = jnp.asarray(nprng.normal(size=(8, 16)).astype(np.float32))
    variables, specs = parallel.sharded_init(model, rng, x, mesh=mesh,
                                             rules=MLP_RULES)
    root = next(iter(variables["params"]))
    w = variables["params"][root]["hidden"]["w"]
    assert tuple(w.sharding.spec) == (None, "model")
    assert specs[root]["hidden"]["w"] == P(None, "model")
    # replicated leaf
    b = variables["params"][root]["out"]["b"]
    assert tuple(b.sharding.spec) == ()


# ---------------------------------------------------------------- ring attn

def _dense_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        t = q.shape[1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(nprng, causal):
    mesh = pt.make_mesh({"data": 2, "seq": 4})
    B, T, H, D = 2, 16, 2, 4
    q = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    k = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    v = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    ring = parallel.make_ring_attention(mesh, seq_axis="seq", causal=causal)
    out = jax.jit(ring)(q, k, v)
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match_dense(nprng):
    mesh = pt.make_mesh({"seq": 8})
    B, T, H, D = 1, 16, 1, 4
    q = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    k = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    v = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    ring = parallel.make_ring_attention(mesh, seq_axis="seq", causal=True)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention(q, k, v, True) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


# ------------------------------------------------------------- multi-host

def test_multihost_initialize_noop_single_process(monkeypatch):
    """initialize() must be a safe no-op without a coordinator (the common
    single-host path) so programs call it unconditionally."""
    from paddle_tpu.parallel import multihost
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    multihost.initialize()
    assert not multihost.is_initialized()


def test_host_sharded_reader_partitions_disjointly(monkeypatch):
    """Each simulated host gets a disjoint slice; the union is the stream
    (the Go master task-queue property, go/master/service.go:368)."""
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.parallel import multihost
    items = list(range(23))
    got = {}
    for hid in range(4):
        monkeypatch.setattr(mesh_lib, "host_count", lambda: 4)
        monkeypatch.setattr(mesh_lib, "host_id", lambda h=hid: h)
        r = multihost.host_sharded_reader(lambda: iter(items))
        got[hid] = list(r())
    allitems = sorted(x for v in got.values() for x in v)
    assert allitems == items
    for a in range(4):
        for b in range(a + 1, 4):
            assert not set(got[a]) & set(got[b])


def test_checkpoint_single_writer(tmp_path, monkeypatch):
    """Non-zero processes must not write checkpoints (single-controller
    write guard); everyone loads the same files."""
    from paddle_tpu.train import checkpoint as ckpt
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    d = ckpt.save_checkpoint(str(tmp_path), 0, {"params": {"w": np.ones(2)}})
    assert not os.path.exists(d)      # nothing written by process 1
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    d = ckpt.save_checkpoint(str(tmp_path), 0, {"params": {"w": np.ones(2)}})
    assert os.path.exists(d)
    out = ckpt.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(out["params"]["w"], np.ones(2))


def test_multihost_mesh_and_trainer_end_to_end():
    """A multihost-style run on the 8-device harness: global mesh + host
    sharded reader + trainer step — the composition the docstring promises."""
    from paddle_tpu import optim
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.nn import costs
    from paddle_tpu.parallel import multihost
    from paddle_tpu.train import Trainer

    mesh = multihost.multihost_mesh()
    assert mesh.devices.size == len(jax.devices())
    rng = np.random.RandomState(0)
    batches = [{"x": rng.normal(size=(16, 28, 28, 1)).astype(np.float32),
                "label": rng.randint(0, 10, 16).astype(np.int32)}
               for _ in range(6)]
    reader = multihost.host_sharded_reader(lambda: iter(batches))
    tr = Trainer(MnistMLP(),
                 lambda o, b: costs.softmax_cross_entropy(o, b["label"]),
                 optim.sgd(0.1), mesh=mesh)
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(reader, num_passes=1, log_period=0)
    assert int(tr.train_state.step) == 6   # single host consumed everything


# ------------------------------------------------------------- ulysses attn

@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(nprng, causal):
    mesh = pt.make_mesh({"data": 2, "seq": 4})
    B, T, H, D = 2, 16, 4, 4           # H=4 divides seq axis size 4
    q = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    k = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    v = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    uly = parallel.make_ulysses_attention(mesh, seq_axis="seq", causal=causal)
    out = jax.jit(uly)(q, k, v)
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_matches_ring(nprng):
    """The two sequence-parallel strategies must agree (same math, different
    collectives) — models can switch by config."""
    mesh = pt.make_mesh({"seq": 8})
    B, T, H, D = 1, 32, 8, 4
    q = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    k = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    v = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    ring = parallel.make_ring_attention(mesh, seq_axis="seq", causal=True)
    uly = parallel.make_ulysses_attention(mesh, seq_axis="seq", causal=True)
    np.testing.assert_allclose(np.asarray(jax.jit(ring)(q, k, v)),
                               np.asarray(jax.jit(uly)(q, k, v)),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_grads_match_dense(nprng):
    mesh = pt.make_mesh({"seq": 8})
    B, T, H, D = 1, 16, 8, 4
    q = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    k = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    v = jnp.asarray(nprng.normal(size=(B, T, H, D)).astype(np.float32))
    uly = parallel.make_ulysses_attention(mesh, seq_axis="seq", causal=True)

    def loss_u(q, k, v):
        return jnp.sum(uly(q, k, v) ** 2)

    def loss_d(q, k, v):
        return jnp.sum(_dense_attention(q, k, v, True) ** 2)

    gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


# ------------------------------------------------------------- pipeline (pp)

def test_pipeline_matches_sequential(nprng):
    """GPipe wavefront over the pipe axis == applying the stages in
    sequence on one device."""
    mesh = pt.make_mesh({"data": 2, "pipe": 4})
    S, M, mb, D = 4, 6, 2, 8
    w = jnp.asarray(nprng.normal(size=(S, D, D)).astype(np.float32) * 0.3)
    b = jnp.asarray(nprng.normal(size=(S, D)).astype(np.float32) * 0.1)
    x = jnp.asarray(nprng.normal(size=(M, mb, D)).astype(np.float32))

    def stage_fn(params, act):
        return jnp.tanh(act @ params["w"] + params["b"])

    pipe = parallel.make_pipeline(mesh, stage_fn)
    got = jax.jit(pipe)({"w": w, "b": b}, x)

    want = x
    for s in range(S):
        want = jnp.tanh(want @ w[s] + b[s])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_pipeline_gradients_match_sequential(nprng):
    mesh = pt.make_mesh({"data": 2, "pipe": 4})
    S, M, mb, D = 4, 5, 2, 6
    w = jnp.asarray(nprng.normal(size=(S, D, D)).astype(np.float32) * 0.3)
    b = jnp.asarray(nprng.normal(size=(S, D)).astype(np.float32) * 0.1)
    x = jnp.asarray(nprng.normal(size=(M, mb, D)).astype(np.float32))

    def stage_fn(params, act):
        return jnp.tanh(act @ params["w"] + params["b"])

    pipe = parallel.make_pipeline(mesh, stage_fn)

    def loss_pipe(params):
        return jnp.sum(pipe(params, x) ** 2)

    def loss_seq(params):
        h = x
        for s in range(S):
            h = jnp.tanh(h @ params["w"][s] + params["b"][s])
        return jnp.sum(h ** 2)

    gp = jax.jit(jax.grad(loss_pipe))({"w": w, "b": b})
    gs = jax.grad(loss_seq)({"w": w, "b": b})
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(gs[k]),
                                   rtol=2e-4, atol=2e-5)


def test_pipeline_1f1b_matches_sequential(nprng):
    """1F1B interleaved schedule: loss and stage-param grads must equal the
    sequential (single-device) oracle — and GPipe+jax.grad."""
    mesh = pt.make_mesh({"data": 2, "pipe": 4})
    S, M, mb, D = 4, 6, 2, 8
    w = jnp.asarray(nprng.normal(size=(S, D, D)).astype(np.float32) * 0.3)
    b = jnp.asarray(nprng.normal(size=(S, D)).astype(np.float32) * 0.1)
    x = jnp.asarray(nprng.normal(size=(M, mb, D)).astype(np.float32))

    def stage_fn(params, act):
        return jnp.tanh(act @ params["w"] + params["b"])

    def loss_fn(out):
        return jnp.sum(out ** 2)

    f1b = parallel.make_pipeline_1f1b(mesh, stage_fn, loss_fn)
    loss, grads = jax.jit(f1b)({"w": w, "b": b}, x)

    def seq_loss(params):
        total = 0.0
        for m in range(M):
            h = x[m]
            for s in range(S):
                h = jnp.tanh(h @ params["w"][s] + params["b"][s])
            total = total + loss_fn(h)
        return total

    want_loss = seq_loss({"w": w, "b": b})
    want_grads = jax.grad(seq_loss)({"w": w, "b": b})
    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=2e-5, atol=2e-6)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(want_grads[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_pipeline_1f1b_many_microbatches(nprng):
    """M >> S (the gradient-accumulation regime 1F1B exists for) stays
    correct: the S-slot activation ring never collides."""
    mesh = pt.make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    S, M, mb, D = 4, 13, 2, 4
    w = jnp.asarray(nprng.normal(size=(S, D, D)).astype(np.float32) * 0.3)
    x = jnp.asarray(nprng.normal(size=(M, mb, D)).astype(np.float32))

    def stage_fn(params, act):
        return jnp.tanh(act @ params["w"])

    def loss_fn(out):
        return jnp.mean(out ** 2)

    f1b = parallel.make_pipeline_1f1b(mesh, stage_fn, loss_fn)
    loss, grads = jax.jit(f1b)({"w": w}, x)

    def seq_loss(params):
        h = x
        for s in range(S):
            h = jnp.tanh(h @ params["w"][s])
        return sum(loss_fn(h[m]) for m in range(M))

    np.testing.assert_allclose(float(loss), float(seq_loss({"w": w})),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(grads["w"]),
                               np.asarray(jax.grad(seq_loss)({"w": w})["w"]),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_1f1b_gates_compute_with_conditionals(nprng):
    """Off-tick events must SKIP stage compute, not run-and-mask it: the
    lowered schedule carries one HLO conditional per event class (forward,
    backward) inside the tick loop, so a device idles on its bubble ticks —
    the ideal M-fwd + M-recompute-vjp 1F1B budget, not 2M+2S-2 of each."""
    mesh = pt.make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    S, M, mb, D = 4, 6, 2, 8
    w = jnp.asarray(nprng.normal(size=(S, D, D)).astype(np.float32) * 0.3)
    x = jnp.asarray(nprng.normal(size=(M, mb, D)).astype(np.float32))

    f1b = parallel.make_pipeline_1f1b(
        mesh, lambda p, a: jnp.tanh(a @ p["w"]), lambda o: jnp.sum(o ** 2))
    txt = jax.jit(f1b).lower({"w": w}, x).as_text()
    n_cond = txt.count("stablehlo.case") + txt.count("stablehlo.if")
    # >= 2 (fwd + bwd gates) rather than == 2: unrelated ops may also lower
    # to conditionals across XLA versions; the numeric 1F1B oracle test is
    # the budget/correctness check
    assert n_cond >= 2, f"expected fwd+bwd conditionals in the tick loop, " \
                        f"found {n_cond}"


def test_seq_parallel_residuals_match_and_use_reduce_scatter(nprng, rng):
    """Megatron tensor parallel with SEQUENCE-PARALLEL residuals
    (``TransformerLM(residual_sharding=...)``): constraining the residual
    stream to a seq-sharded spec must (a) leave the logits numerically
    identical to the unsharded model and (b) make XLA lower the tp
    activation sync as reduce-scatter/all-gather pairs instead of
    all-reduces — the halved-wire-bytes recipe
    ``experiments/scaling_projection.py`` projects at scale."""
    from jax.sharding import NamedSharding

    from paddle_tpu.models import TransformerLM

    mesh = pt.make_mesh({"data": 2, "model": 4})
    V, D, T, B = 64, 32, 16, 4
    kw = dict(vocab=V, dim=D, num_layers=2, num_heads=4, ffn_hidden=64,
              max_len=T)
    base = TransformerLM(**kw)
    ids = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)
    variables = base.init(jax.random.PRNGKey(0), ids)
    ref = base.apply(variables, ids)

    rules = parallel.ShardingRules([
        ("*/attn/wq", P(None, "model")), ("*/attn/wk", P(None, "model")),
        ("*/attn/wv", P(None, "model")), ("*/attn/wo", P("model", None)),
        ("*/ffn1/w", P(None, "model")), ("*/ffn1/b", P("model")),
        ("*/ffn2/w", P("model", None)),
    ])
    params = parallel.shard_tree(mesh, variables["params"],
                                 rules(variables["params"]))

    def seq_sharded(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("data", "model", None)))

    sp = TransformerLM(**kw, residual_sharding=seq_sharded)
    inp = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    f = jax.jit(lambda p, i: sp.apply({"params": p}, i))
    np.testing.assert_allclose(np.asarray(f(params, inp)), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    # The constraint must change the lowering: the tp-only forward syncs its
    # partial sums with per-sublayer all-reduces; seq-sharding the residuals
    # re-expresses those syncs in scattered form (reduce-scatter, or
    # all-gather pairs — the exact mix is XLA's cost-model choice; the wire
    # accounting lives in experiments/scaling_projection.py).
    def n_allreduce(fn):
        hlo = fn.lower(params, inp).compile().as_text()
        return hlo.count(" all-reduce(") + hlo.count(" all-reduce-start(")

    f_tp = jax.jit(lambda p, i: base.apply({"params": p}, i))
    assert n_allreduce(f) < n_allreduce(f_tp), \
        "seq-sharded residuals should eliminate tp activation all-reduces"


def test_megatron_sp_matches_unsharded_lm(nprng, rng):
    """Explicit Megatron tp + sequence-parallel residuals
    (``parallel.make_megatron_sp_lm_apply``): logits, loss, AND grads must
    equal the standard unsharded TransformerLM on the same variables tree,
    and the lowering must carry the hand-written AG/RS pairs with NO
    activation all-reduces. (AG+RS moves the same wire as the all-reduce
    it replaces — the recipe's win is T/tp-sharded residuals/LayerNorms/
    activation memory, which pjit's partitioner does not produce.)"""
    from jax.sharding import NamedSharding

    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs

    mesh = pt.make_mesh({"data": 2, "model": 4})
    V, D, T, B, H = 64, 32, 16, 4, 4
    model = TransformerLM(vocab=V, dim=D, num_layers=2, num_heads=H,
                          ffn_hidden=64, max_len=T)
    ids = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    ref = model.apply(variables, ids)

    params = parallel.shard_tree(mesh, variables["params"],
                                 parallel.megatron_sp_rules()(
                                     variables["params"]))
    inp = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    apply_fn = parallel.make_megatron_sp_lm_apply(model, mesh)
    f = jax.jit(lambda p, i: apply_fn({"params": p}, i))
    np.testing.assert_allclose(np.asarray(f(params, inp)), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    # grads through the shard_map (AG/RS transpose pair) == plain grads
    tgt = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)

    loss_fn_sp = parallel.make_megatron_sp_lm_apply(model, mesh,
                                                    with_loss=True)

    def loss_sp(p, i):
        return loss_fn_sp({"params": p}, i, tgt)

    def loss_ref(p):
        lg = model.apply({"params": p}, ids)
        return jnp.mean(costs.softmax_cross_entropy(
            lg.reshape(-1, V), tgt.reshape(-1)))

    g_sp = jax.jit(jax.grad(loss_sp))(params, inp)
    g_ref = jax.grad(loss_ref)(variables["params"])
    for a, b in zip(jax.tree_util.tree_leaves(g_sp),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)

    def count_ar(hlo):
        return hlo.count(" all-reduce(") + hlo.count(" all-reduce-start(")

    # The TRAINING path's only all-reduces are the loss/count psums — the
    # activation syncs are hand-written AG/RS. Compare RELATIVELY against
    # the tp-only pjit lowering of the same loss on the same sharded
    # params, which pays per-sublayer activation all-reduces (the sibling
    # residual-sharding test uses the same relative form): an absolute
    # budget pins XLA's exact op count and rots across versions.
    hlo = jax.jit(loss_sp).lower(params, inp).compile().as_text()
    assert "reduce-scatter" in hlo, \
        "explicit Megatron-SP training must carry reduce-scatter syncs"

    def loss_tp(p, i):
        lg = model.apply({"params": p}, i)
        return jnp.mean(costs.softmax_cross_entropy(
            lg.reshape(-1, V), tgt.reshape(-1)))

    n_sp = count_ar(hlo)
    n_tp = count_ar(jax.jit(loss_tp).lower(params, inp).compile().as_text())
    assert n_sp < n_tp, \
        f"explicit SP loss path should carry fewer all-reduces than the " \
        f"tp-only pjit lowering (activation ARs reintroduced?): " \
        f"{n_sp} vs {n_tp}"
    fwd_hlo = jax.jit(lambda p, i: apply_fn({"params": p}, i)).lower(
        params, inp).compile().as_text()
    assert "all-gather" in fwd_hlo and "reduce-scatter" in fwd_hlo, \
        "explicit Megatron-SP must lower to all-gather + reduce-scatter"
    assert " all-reduce(" not in fwd_hlo, \
        "forward should carry no activation all-reduce"


def test_pipeline_loss_form_matches_sequential(nprng):
    """``make_pipeline_loss``: the GPipe wavefront closing the loss on the
    LAST stage (scalar psum) must reproduce the sequential loss AND the
    grads of stage params, final (head) params, and the input stack — and
    its lowering must NOT broadcast the [M, mb, D] output stack over the
    pipe axis (1.07 GB/step at the d1024 shape; the scalar psum is the
    point of the loss form)."""
    mesh = pt.make_mesh({"data": 2, "pipe": 4})
    S, M, mbg, Din = 4, 6, 4, 8
    w = jnp.asarray(nprng.normal(size=(S, Din, Din)).astype(np.float32) * .3)
    wh = jnp.asarray(nprng.normal(size=(Din, 3)).astype(np.float32) * .5)
    x = jnp.asarray(nprng.normal(size=(M, mbg, Din)).astype(np.float32))
    y = jnp.asarray(nprng.normal(size=(M, mbg, 3)).astype(np.float32))

    def stage_fn(p, a):
        return jnp.tanh(a @ p["w"])

    def final_fn(fp, outbuf, tgt):
        return jnp.sum((outbuf @ fp["wh"] - tgt) ** 2)

    pipe_loss = parallel.make_pipeline_loss(
        mesh, stage_fn, final_fn,
        x_spec=P(None, "data", None), extra_specs=(P(None, "data", None),),
        reduce_axes=("data",))

    def loss_sp(sp_, fp, x, y):
        return pipe_loss(sp_, fp, x, y)

    def loss_seq(sp_, fp, x, y):
        h = x
        for s in range(S):
            h = jnp.tanh(h @ sp_["w"][s])
        return jnp.sum((h @ fp["wh"] - y) ** 2)

    args = ({"w": w}, {"wh": wh}, x, y)
    got = jax.jit(loss_sp)(*args)
    want = loss_seq(*args)
    np.testing.assert_allclose(float(got), float(want), rtol=2e-5)

    g_sp = jax.jit(jax.grad(loss_sp, argnums=(0, 1, 2)))(*args)
    g_seq = jax.grad(loss_seq, argnums=(0, 1, 2))(*args)
    for a, b in zip(jax.tree_util.tree_leaves(g_sp),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    # no [M, mb, D]-sized all-reduce: every all-reduce buffer in the loss
    # HLO must be orders below the output stack's element count
    import re as _re
    hlo = jax.jit(loss_sp).lower(*args).compile().as_text()
    stack_elems = M * mbg * Din
    for line in hlo.splitlines():
        m = _re.search(r"f32\[([\d,]*)\]\{[^}]*\}? all-reduce", line)
        if m:
            n = 1
            for d in m.group(1).split(","):
                if d:
                    n *= int(d)
            assert n < stack_elems, \
                f"loss form should not broadcast the output stack: {line}"


def test_megatron_sp_bf16_comm_close_to_exact(nprng, rng):
    """comm_dtype=bfloat16 (the Megatron-standard wire compression —
    halves tp activation bytes vs the policy's f32 Linear outputs) must
    stay within bf16 tolerance of the exact unsharded loss."""
    from jax.sharding import NamedSharding

    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs

    mesh = pt.make_mesh({"data": 2, "model": 4})
    V, D, T, B, H = 64, 32, 16, 4, 4
    model = TransformerLM(vocab=V, dim=D, num_layers=2, num_heads=H,
                          ffn_hidden=64, max_len=T)
    ids = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)
    tgt = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    ref_loss = jnp.mean(costs.softmax_cross_entropy(
        model.apply(variables, ids).reshape(-1, V), tgt.reshape(-1)))

    params = parallel.shard_tree(mesh, variables["params"],
                                 parallel.megatron_sp_rules()(
                                     variables["params"]))
    inp = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    loss_fn = parallel.make_megatron_sp_lm_apply(
        model, mesh, with_loss=True, comm_dtype=jnp.bfloat16)
    got = jax.jit(lambda p, i: loss_fn({"params": p}, i, tgt))(params, inp)
    np.testing.assert_allclose(float(got), float(ref_loss), rtol=2e-2)


def test_pipeline_loss_bf16_comm_close_to_exact(nprng):
    """comm_dtype=bfloat16 on the inter-stage hops stays within bf16
    tolerance of the exact pipeline loss."""
    mesh = pt.make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    S, M, mbg, Din = 4, 6, 4, 8
    w = jnp.asarray(nprng.normal(size=(S, Din, Din)).astype(np.float32) * .3)
    wh = jnp.asarray(nprng.normal(size=(Din, 3)).astype(np.float32) * .5)
    x = jnp.asarray(nprng.normal(size=(M, mbg, Din)).astype(np.float32))
    y = jnp.asarray(nprng.normal(size=(M, mbg, 3)).astype(np.float32))

    def stage_fn(p, a):
        return jnp.tanh(a.astype(jnp.float32) @ p["w"])

    def final_fn(fp, outbuf, tgt):
        return jnp.sum((outbuf @ fp["wh"] - tgt) ** 2)

    exact = parallel.make_pipeline_loss(
        mesh, stage_fn, final_fn, extra_specs=(P(),))
    comp = parallel.make_pipeline_loss(
        mesh, stage_fn, final_fn, extra_specs=(P(),),
        comm_dtype=jnp.bfloat16)
    le = jax.jit(exact)({"w": w}, {"wh": wh}, x, y)
    lc = jax.jit(comp)({"w": w}, {"wh": wh}, x, y)
    np.testing.assert_allclose(float(lc), float(le), rtol=3e-2)


def test_megatron_sp_flash_matches_unsharded_lm(nprng, rng):
    """The megatron-SP kernel's use_flash=True path (per-device Pallas
    flash attention on the local head group, interpreter mode off-TPU)
    must match the unsharded model like the einsum path does."""
    from jax.sharding import NamedSharding

    from paddle_tpu.models import TransformerLM

    mesh = pt.make_mesh({"data": 2, "model": 4})
    V, D, T, B, H = 64, 32, 16, 4, 4
    model = TransformerLM(vocab=V, dim=D, num_layers=2, num_heads=H,
                          ffn_hidden=64, max_len=T)
    ids = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    ref = model.apply(variables, ids)

    params = parallel.shard_tree(mesh, variables["params"],
                                 parallel.megatron_sp_rules()(
                                     variables["params"]))
    inp = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    apply_fn = parallel.make_megatron_sp_lm_apply(model, mesh,
                                                  use_flash=True)
    got = jax.jit(lambda p, i: apply_fn({"params": p}, i))(params, inp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_megatron_sp_bf16_policy_matches_pjit(nprng, rng):
    """Mixed-precision parity (ISSUE 1 satellite 1): under
    ``use_policy(bfloat16_compute)`` the explicit Megatron-SP path must
    apply the SAME policy casts as the pjit path's Linears (cast_compute
    operands, accumulate in accum_dtype) — the two lowerings of one model
    must agree to bf16 tolerance, not silently diverge because the explicit
    kernel ran f32."""
    from jax.sharding import NamedSharding

    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs

    mesh = pt.make_mesh({"data": 2, "model": 4})
    V, D, T, B, H = 64, 32, 16, 4, 4
    model = TransformerLM(vocab=V, dim=D, num_layers=2, num_heads=H,
                          ffn_hidden=64, max_len=T)
    ids = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)
    tgt = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    params = parallel.shard_tree(mesh, variables["params"],
                                 parallel.megatron_sp_rules()(
                                     variables["params"]))
    inp = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    tgt_s = jax.device_put(tgt, NamedSharding(mesh, P("data", None)))

    # build the factory OUTSIDE the policy context, trace INSIDE: the
    # policy must be read at trace time (as nn.layers.Linear reads it),
    # not captured when the factory ran
    loss_fn = parallel.make_megatron_sp_lm_apply(model, mesh,
                                                 with_loss=True)
    with use_policy(bfloat16_compute):
        got = float(jax.jit(loss_fn)({"params": params}, inp, tgt_s))

        def pjit_loss(p):
            lg = model.apply({"params": p}, ids)
            return jnp.mean(costs.softmax_cross_entropy(
                lg.reshape(-1, V).astype(jnp.float32), tgt.reshape(-1)))

        want = float(jax.jit(pjit_loss)(variables["params"]))
    # both paths multiply bf16 operands with f32 accumulation; residual
    # collectives reorder sums, so policy tolerance, not bit equality
    np.testing.assert_allclose(got, want, rtol=5e-3)
    # sanity: the bf16-policy loss must differ from an f32 trace by MORE
    # than f32 roundoff (i.e. the casts actually happened)
    f32_loss = parallel.make_megatron_sp_lm_apply(model, mesh,
                                                  with_loss=True)
    exact = float(jax.jit(f32_loss)({"params": params}, inp, tgt_s))
    assert got != exact, "bf16 policy had no effect on the explicit path"


def test_megatron_sp_remat_matches(nprng, rng):
    """remat="dots" on the explicit Megatron-SP path (layer loop as a
    jax.checkpoint'd lax.scan over stacked shard params) reproduces the
    unrolled loop's loss and grads."""
    from jax.sharding import NamedSharding

    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs

    mesh = pt.make_mesh({"data": 2, "model": 4})
    V, D, T, B, H = 64, 32, 16, 4, 4
    model = TransformerLM(vocab=V, dim=D, num_layers=3, num_heads=H,
                          ffn_hidden=64, max_len=T)
    ids = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)
    tgt = jnp.asarray(nprng.randint(0, V, (B, T)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    params = parallel.shard_tree(mesh, variables["params"],
                                 parallel.megatron_sp_rules()(
                                     variables["params"]))
    inp = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    tgt_s = jax.device_put(tgt, NamedSharding(mesh, P("data", None)))

    plain = parallel.make_megatron_sp_lm_apply(model, mesh, with_loss=True)
    remat = parallel.make_megatron_sp_lm_apply(model, mesh, with_loss=True,
                                               remat="dots")
    lp = jax.jit(plain)({"params": params}, inp, tgt_s)
    lr = jax.jit(remat)({"params": params}, inp, tgt_s)
    np.testing.assert_allclose(float(lr), float(lp), rtol=1e-6)
    gp = jax.jit(jax.grad(lambda p: plain({"params": p}, inp, tgt_s)))(
        params)
    gr = jax.jit(jax.grad(lambda p: remat({"params": p}, inp, tgt_s)))(
        params)
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_pipeline_loss_bubble_nonfinite_safe(nprng):
    """Bubble devices run final_fn on a zero output buffer; a non-finite
    value there (0/0 normalisation, log 0, ...) must NOT poison the psum —
    regression for the ``val * mask`` NaN*0 masking (ISSUE 1 satellite 2:
    now jnp.where-selected)."""
    mesh = pt.make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    S, M, mbg, Din = 4, 6, 4, 8
    w = jnp.asarray(nprng.normal(size=(S, Din, Din)).astype(np.float32) * .3)
    x = jnp.asarray(nprng.normal(size=(M, mbg, Din)).astype(np.float32))

    def stage_fn(p, a):
        return jnp.tanh(a @ p["w"])

    def final_fn(fp, outbuf):
        # 0/0 on bubble devices (their outbuf is all zeros): mean over the
        # buffer's nonzero entries — NaN on every stage but the last
        nz = jnp.sum(jnp.abs(outbuf) > 0)
        return jnp.sum(outbuf * fp["v"]) / nz

    fp = {"v": jnp.asarray(nprng.normal(size=(Din,)).astype(np.float32))}
    loss_sp = parallel.make_pipeline_loss(mesh, stage_fn, final_fn)
    got = float(jax.jit(loss_sp)({"w": w}, fp, x))
    assert np.isfinite(got), "bubble-device NaN poisoned the psum"
    # the BACKWARD must survive too: an outer where alone still multiplies
    # the zeroed cotangent into final_fn's inf partials (0 * inf = NaN) —
    # the double-where (safe bubble input) keeps stage grads finite
    grads = jax.jit(jax.grad(lambda sp: loss_sp(sp, fp, x)))({"w": w})
    for g in jax.tree_util.tree_leaves(grads):
        assert np.isfinite(np.asarray(g)).all(), \
            "bubble-device NaN poisoned the backward"

    # sequential oracle
    def seq(w, fp, x):
        outs = []
        for m in range(M):
            a = x[m]
            for s in range(S):
                a = jnp.tanh(a @ w[s])
            outs.append(a)
        ob = jnp.stack(outs)
        return jnp.sum(ob * fp["v"]) / jnp.sum(jnp.abs(ob) > 0)

    want = float(seq(w, fp, x))
    np.testing.assert_allclose(got, want, rtol=2e-5)
