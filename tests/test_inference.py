"""Model IR + export/inference tests — the analog of the reference's
merged-model deployment (MergeModel.cpp + C-API inference) and config
round-trips (config_parser -> ModelConfig -> GradientMachine::create)."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.nn as nn
from paddle_tpu.core.module import Module, Sequential
from paddle_tpu.core import config as config_lib
from paddle_tpu.inference import export, infer, load_inference_model
from paddle_tpu.models import (LeNet, Seq2SeqAttention, SparseLR,
                               WideDeepCTR, resnet_cifar)


class TinyMLP(Module):
    def __init__(self, hidden=16, classes=4, name=None):
        super().__init__(name=name)
        self.h = nn.Linear(hidden, act="relu", name="h")
        self.out = nn.Linear(classes, name="out")

    def forward(self, x, train=False):
        return self.out(self.h(x))


class TiedPair(Module):
    """Same Linear instance applied twice — weight sharing must survive the
    config round-trip as a shared reference."""

    def __init__(self, dim=8, name=None):
        super().__init__(name=name)
        self.shared = nn.Linear(dim, name="shared")

    def forward(self, x, train=False):
        return self.shared(self.shared(x))


def test_config_roundtrip_rebuilds_identical_model(rng):
    model = TinyMLP(hidden=12, classes=3)
    cfg = config_lib.module_config(model)
    text = config_lib.config_to_json(cfg)
    rebuilt = config_lib.build_module(config_lib.config_from_json(text))
    x = jnp.ones((2, 5))
    v1 = model.init(rng, x)
    v2 = rebuilt.init(rng, x)
    np.testing.assert_array_equal(np.asarray(model.apply(v1, x)),
                                  np.asarray(rebuilt.apply(v2, x)))


@pytest.mark.parametrize("factory,sample", [
    (lambda: TinyMLP(), np.ones((2, 5), np.float32)),
    (lambda: LeNet(), np.ones((2, 28, 28, 1), np.float32)),
    (lambda: resnet_cifar(depth_n=1), np.ones((2, 32, 32, 3), np.float32)),
    (lambda: SparseLR(4, 11), np.zeros((3, 4), np.int32)),
    (lambda: WideDeepCTR(4, 11, emb_dim=4, hidden=(8,)),
     np.zeros((3, 4), np.int32)),
])
def test_export_reload_bitwise_equal_forward(tmp_path, rng, factory, sample):
    model = factory()
    x = jnp.asarray(sample)
    variables = model.init(rng, x, train=True)
    path = os.path.join(str(tmp_path), "bundle")
    export(path, model, variables)
    loaded = load_inference_model(path)
    want = np.asarray(jax.jit(
        lambda v, x: model.apply(v, x))(variables, x))
    got = np.asarray(loaded.predict(x))
    np.testing.assert_array_equal(want, got)   # bitwise


def test_export_reload_seq2seq_beam_decode(tmp_path, rng):
    model = Seq2SeqAttention(src_vocab=20, tgt_vocab=18, emb_dim=8,
                             hidden=8)
    src = jnp.asarray(np.random.RandomState(0).randint(1, 20, size=(2, 6)))
    src_len = jnp.asarray([6, 4])
    batch = {"src": src, "src_len": src_len,
             "tgt": jnp.zeros((2, 6), jnp.int32),
             "tgt_len": jnp.asarray([5, 5])}
    variables = model.init_variables(rng, batch)
    path = os.path.join(str(tmp_path), "nmt")
    export(path, model, variables)
    loaded = load_inference_model(path)
    want_tok, want_sc = model.generate(variables, src, src_len, beam_size=3,
                                       max_len=7)
    got_tok, got_sc = loaded.predict(src, src_len, K=3, max_len=7,
                                     length_penalty=0.0,
                                     method="_beam_search")
    np.testing.assert_array_equal(np.asarray(want_tok), np.asarray(got_tok))
    np.testing.assert_allclose(np.asarray(want_sc), np.asarray(got_sc),
                               rtol=1e-6)


def test_weight_sharing_survives_roundtrip(rng):
    model = TiedPair(dim=6)
    cfg = config_lib.module_config(model)
    rebuilt = config_lib.build_module(cfg)
    assert rebuilt.shared is not None
    x = jnp.ones((2, 6))
    v = rebuilt.init(rng, x)
    # one shared Linear: exactly one param subtree
    root = next(iter(v["params"]))
    assert list(v["params"][root].keys()) == ["shared"]
    np.testing.assert_array_equal(
        np.asarray(model.apply(model.init(rng, x), x)),
        np.asarray(rebuilt.apply(v, x)))


def test_untrusted_class_refused(tmp_path):
    cfg = {"format": 1, "root": 0, "modules": [
        {"class": "os:system", "args": [], "kwargs": {}}]}
    with pytest.raises(ValueError, match="untrusted"):
        config_lib.build_module(cfg)


def test_corrupt_export_detected(tmp_path, rng):
    model = TinyMLP()
    x = jnp.ones((2, 5))
    variables = model.init(rng, x)
    path = os.path.join(str(tmp_path), "bundle")
    export(path, model, variables)
    with open(os.path.join(path, "variables.npz"), "r+b") as f:
        f.seek(50)
        f.write(b"\xff\xff\xff")
    with pytest.raises(IOError, match="crc"):
        load_inference_model(path)


def test_infer_convenience(tmp_path, rng):
    model = TinyMLP()
    x = jnp.ones((2, 5))
    variables = model.init(rng, x)
    path = os.path.join(str(tmp_path), "bundle")
    export(path, model, variables)
    out = infer(path, x)
    assert out.shape == (2, 4)


def test_model_diagram_dot_output():
    from paddle_tpu.inference import model_diagram
    from paddle_tpu.models import MnistMLP
    dot = model_diagram(MnistMLP())
    assert dot.startswith("digraph model {") and dot.endswith("}")
    assert "Linear" in dot and "->" in dot


def test_from_torch_state_dict_roundtrip():
    """torch2paddle analog: a torch MLP's weights produce identical outputs
    through the converted paddle_tpu model."""
    import numpy as np
    torch = pytest.importorskip("torch")
    import torch.nn as tnn
    import jax.numpy as jnp
    from paddle_tpu.core.module import Module
    from paddle_tpu.nn.layers import Linear
    from paddle_tpu.utils.interop import from_torch_state_dict

    tmodel = tnn.Sequential(tnn.Linear(8, 16), tnn.ReLU(), tnn.Linear(16, 4))
    tmodel.eval()

    class Mlp(Module):
        def __init__(self):
            super().__init__()
            self.fc1 = Linear(16, act="relu")
            self.fc2 = Linear(4)

        def forward(self, x):
            return self.fc2(self.fc1(x))

    m = Mlp()
    import jax
    v = m.init(jax.random.PRNGKey(0), jnp.ones((1, 8)))
    root = next(iter(v["params"]))
    conv = from_torch_state_dict(
        tmodel.state_dict(),
        rules=[("0", f"{root}/fc1"), ("2", f"{root}/fc2")],
        kinds={"0": "linear", "2": "linear"})

    x = np.random.RandomState(0).normal(size=(3, 8)).astype(np.float32)
    with torch.no_grad():
        want = tmodel(torch.from_numpy(x)).numpy()
    got = np.asarray(m.apply({"params": conv["params"]}, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_from_torch_conv_and_bn():
    import numpy as np
    torch = pytest.importorskip("torch")
    import torch.nn as tnn
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.module import Module
    from paddle_tpu.nn.layers import BatchNorm, Conv2D
    from paddle_tpu.utils.interop import from_torch_state_dict

    tconv = tnn.Conv2d(3, 5, 3, padding=1)
    tbn = tnn.BatchNorm2d(5)
    tbn.running_mean.normal_(); tbn.running_var.uniform_(0.5, 2.0)
    tmodel = tnn.Sequential(tconv, tbn).eval()

    class Net(Module):
        def __init__(self):
            super().__init__()
            self.conv = Conv2D(5, kernel=3, padding="SAME")
            self.bn = BatchNorm()

        def forward(self, x, train=False):
            return self.bn(self.conv(x), train=train)

    m = Net()
    v = m.init(jax.random.PRNGKey(0), jnp.ones((1, 6, 6, 3)))
    root = next(iter(v["params"]))
    conv = from_torch_state_dict(
        tmodel.state_dict(),
        rules=[("0", f"{root}/conv"), ("1", f"{root}/bn")],
        kinds={"0": "conv2d", "1": "batchnorm"})

    x = np.random.RandomState(1).normal(size=(2, 6, 6, 3)).astype(np.float32)
    with torch.no_grad():
        want = tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    got = np.asarray(m.apply(
        {"params": conv["params"], "state": conv["state"]},
        jnp.asarray(x)))
    np.testing.assert_allclose(got.transpose(0, 3, 1, 2), want,
                               rtol=1e-4, atol=1e-5)
