"""Multi-process mapper (VERDICT r4 #8) — the xmap_readers analog
(reference: ``v2/reader/decorator.py:233-292``; image loader
``utils/image_multiproc.py``). Correctness is asserted everywhere; the
speedup assertion only runs on multi-core hosts (the bench host has one
core, where process parallelism cannot win)."""

import multiprocessing as mp
import os
import time

import numpy as np
import pytest

import xmap_helpers as H
from paddle_tpu import data
from paddle_tpu.data import image as im


def _ints(n):
    def reader():
        return iter(range(n))
    return reader


def test_xmap_ordered_matches_serial():
    got = list(data.xmap(H.slow_square, _ints(12), processes=2)())
    assert got == [x * x for x in range(12)]


def test_xmap_unordered_same_multiset():
    got = list(data.xmap(H.slow_square, _ints(12), processes=2,
                         ordered=False)())
    assert sorted(got) == [x * x for x in range(12)]


def test_xmap_worker_error_propagates():
    with pytest.raises(RuntimeError, match="sample 3 is poison"):
        list(data.xmap(H.boom_on_3, _ints(8), processes=2)())


def test_xmap_dead_worker_raises_instead_of_hanging():
    """A worker killed without cleanup (segfault/OOM-kill analog) must be
    detected as a corpse, not waited on forever."""
    with pytest.raises(RuntimeError, match="died with exitcode"):
        list(data.xmap(H.die_hard, _ints(8), processes=1)())


def test_xmap_source_reader_error_propagates_no_hang():
    """A source reader that raises mid-iteration must surface the error
    after the mapped results — never strand the consumer on a queue."""
    def flaky():
        def it():
            yield from range(5)
            raise IOError("disk went away")
        return it()
    with pytest.raises(IOError, match="disk went away"):
        list(data.xmap(H.square, flaky, processes=2)())


def test_xmap_early_abandon_shuts_down_workers():
    it = data.xmap(H.square, _ints(1000), processes=2, buffer=4)()
    got = [next(it) for _ in range(3)]
    assert got == [0, 1, 4]
    it.close()
    deadline = time.time() + 10
    while time.time() < deadline and mp.active_children():
        time.sleep(0.1)
    assert not mp.active_children()


def test_xmap_train_augment_pickles_and_is_worker_independent():
    """TrainAugment crosses the process boundary and its per-sample rng
    (seeded from the image bytes) gives results independent of worker
    count or assignment."""
    rng = np.random.RandomState(0)
    imgs = [rng.rand(10, 8, 3).astype(np.float32) for _ in range(6)]

    def rdr():
        return iter(imgs)

    tf = im.TrainAugment((4, 4), (6, 6), mean=[0, 0, 0], seed=7)
    serial = [tf(x) for x in imgs]
    par1 = list(data.xmap(tf, rdr, processes=1)())
    par2 = list(data.xmap(tf, rdr, processes=2)())
    for s, a, b in zip(serial, par1, par2):
        np.testing.assert_array_equal(s, a)
        np.testing.assert_array_equal(s, b)
    # cross-epoch diversity: set_epoch reseeds the per-sample draws
    epoch1 = [tf.set_epoch(1)(x) for x in imgs]
    assert any(not np.array_equal(s, e) for s, e in zip(serial, epoch1))


@pytest.mark.skipif((os.cpu_count() or 1) < 4,
                    reason="speedup needs a multi-core host; the bench "
                           "host has one core (correctness is asserted "
                           "in the other tests)")
def test_xmap_beats_thread_map_on_cpu_bound_mapper():
    n = 48
    t0 = time.perf_counter()
    serial = [H.burn(x) for x in range(n)]
    t_serial = time.perf_counter() - t0
    t0 = time.perf_counter()
    par = list(data.xmap(H.burn, _ints(n), processes=4, buffer=16)())
    t_par = time.perf_counter() - t0
    assert par == serial
    assert t_par < t_serial, (t_par, t_serial)
