"""Transport + process-replica tests (ISSUE 13): the length-prefixed
frame protocol (timeout / corruption / EOF classified, never raised
through the router as a crash), seq-numbered at-least-once delivery with
child-side dedupe (a lost or garbled REPLY never re-executes the work),
and one real end-to-end subprocess replica serving oracle-identical
tokens through the fleet.

The protocol tests run ``serve_loop`` in a thread over ``os.pipe`` pairs
with fake engine/scheduler objects — the dedupe/injection machinery is
pure host logic and must be testable without paying a jax child spawn.
"""

import os
import tempfile
import threading

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.serve import transport as tp
from paddle_tpu.serve.replica_proc import (EventBuffer, SettableClock,
                                           load_variables_npz,
                                           save_variables_npz,
                                           serve_loop)

V, W = 64, 24


# ---------------------------------------------------------------------------
# framing: round-trip, classification of every failure mode
# ---------------------------------------------------------------------------

def _pipe_pair():
    r, w = os.pipe()
    return os.fdopen(r, "rb"), os.fdopen(w, "wb")


def test_frame_roundtrip_and_numpy_coercion():
    rf, wf = _pipe_pair()
    msg = {"op": "tick", "seq": 3, "prompt": [np.int64(7), 2],
           "now": np.float64(1.5), "text": "héllo"}
    tp.write_frame(wf, msg)
    got = tp.FrameReader(rf).read_frame(timeout_s=1.0)
    assert got == {"op": "tick", "seq": 3, "prompt": [7, 2],
                   "now": 1.5, "text": "héllo"}
    rf.close(), wf.close()


def test_frame_reader_classifies_corrupt_timeout_closed():
    # corrupt body: valid length prefix, non-JSON payload
    rf, wf = _pipe_pair()
    wf.write(tp._HEADER.pack(4) + b"\xff\xfe\x00\x01")
    wf.flush()
    with pytest.raises(tp.TransportCorrupt):
        tp.FrameReader(rf).read_frame(timeout_s=1.0)
    rf.close(), wf.close()
    # absurd length prefix
    rf, wf = _pipe_pair()
    wf.write(tp._HEADER.pack(tp.MAX_FRAME_BYTES + 1))
    wf.flush()
    with pytest.raises(tp.TransportCorrupt):
        tp.FrameReader(rf).read_frame(timeout_s=1.0)
    rf.close(), wf.close()
    # timeout: nothing arrives; partial bytes stay buffered
    rf, wf = _pipe_pair()
    reader = tp.FrameReader(rf)
    with pytest.raises(tp.TransportTimeout):
        reader.read_frame(timeout_s=0.05)
    tp.write_frame(wf, {"seq": 1})
    assert reader.read_frame(timeout_s=1.0) == {"seq": 1}
    rf.close(), wf.close()
    # EOF
    rf, wf = _pipe_pair()
    wf.close()
    with pytest.raises(tp.TransportClosed):
        tp.FrameReader(rf).read_frame(timeout_s=1.0)
    rf.close()


# ---------------------------------------------------------------------------
# serve_loop protocol: dedupe + injected reply loss/corruption
# ---------------------------------------------------------------------------

class _FakeCache:
    free_blocks = 7
    num_blocks = 8
    block_size = 4
    prefix_hit_blocks = 0
    cow_forks = 0


class _FakeEngine:
    """Just enough engine surface for serve_loop's load/stats paths."""
    max_slots = 2
    ticks = 0
    tokens_generated = 0
    cache = _FakeCache()
    context_width = W

    def free_slots(self):
        return [0, 1]

    def compile_counts(self):
        return {"prefill": 1, "tick": 1}


class _FakeScheduler:
    """Counts step() calls — the at-least-once dedupe assertion is that
    an injected reply loss never double-steps."""

    def __init__(self):
        self.steps = 0
        self.est_tick_s = 0.1
        self.queue, self.running, self.prefilling = [], {}, {}
        self.completed = []
        self.submitted = []

    def step(self):
        self.steps += 1
        return False

    def submit(self, prompt, max_new_tokens, **kw):
        self.submitted.append((list(prompt), max_new_tokens, kw))

    def pending_new_tokens(self):
        return 0

    def load_report(self):
        return {"pending_new_tokens": 0, "running": 0, "queued": 0,
                "prefilling": 0}


def _loopback(tmpdir):
    """serve_loop in a thread over two pipes; returns the parent-side
    transport + the fakes."""
    c2p_r, c2p_w = _pipe_pair()          # child -> parent
    p2c_r, p2c_w = _pipe_pair()          # parent -> child
    eng, sched = _FakeEngine(), _FakeScheduler()
    t = threading.Thread(
        target=serve_loop, args=(p2c_r, c2p_w),
        kwargs=dict(engine=eng, sched=sched, buf=EventBuffer(),
                    clock=SettableClock(), root=tmpdir, replica_id=0),
        daemon=True)
    t.start()
    tr = tp.ReplicaTransport(c2p_r, p2c_w, timeout_s=0.5)
    return tr, eng, sched, t


def test_serve_loop_at_least_once_dedupe_on_lost_reply(tmp_path):
    tr, eng, sched, t = _loopback(str(tmp_path))
    hello = tr.request("hello", now=0.0)
    assert hello["ok"] and hello["context_width"] == W
    # injected reply loss: the child does the work, the reply vanishes;
    # the parent times out, retransmits the SAME seq, and receives the
    # CACHED reply — the tick ran exactly once
    reply = tr.request("tick", now=0.1, tick=0, inject_drop_reply=True)
    assert reply["ok"] and sched.steps == 1
    assert tr.timeouts == 1 and tr.retransmits == 1
    # injected corruption: classified, retransmitted, recovered — and
    # still exactly one more step
    reply = tr.request("tick", now=0.2, tick=1,
                       inject_corrupt_reply=True)
    assert reply["ok"] and sched.steps == 2
    assert tr.corrupt_replies == 1 and tr.retransmits == 2
    # duplicate submit acks as duplicate (rid idempotency child-side)
    a = tr.request("submit", rid=5, prompt=[1, 2], max_new_tokens=3,
                   now=0.3)
    b = tr.request("submit", rid=5, prompt=[1, 2], max_new_tokens=3,
                   now=0.3)
    assert a["ok"] and not a["duplicate"]
    assert b["ok"] and b["duplicate"]
    assert len(sched.submitted) == 1
    # heartbeat landed under the root with the load payload
    from paddle_tpu.parallel import multihost
    beats = multihost.read_heartbeats(str(tmp_path))
    assert beats[0]["role"] == "serving-replica"
    assert "pending_new_tokens" in beats[0]
    stop = tr.request("stop")
    assert stop["ok"]
    t.join(timeout=5.0)
    assert not t.is_alive()
    tr.close()


def test_serve_loop_drain_returns_queued_rids(tmp_path):
    tr, eng, sched, t = _loopback(str(tmp_path))
    tr.request("hello", now=0.0)

    class _Q:
        def __init__(self, rid):
            self.rid = rid
    sched.queue = [_Q(3), _Q(4)]
    reply = tr.request("drain", now=0.1)
    assert reply["queued_rids"] == [3, 4]
    assert sched.queue == []
    # a draining replica refuses fresh submissions (the drain contract)
    ref = tr.request("submit", rid=9, prompt=[1], max_new_tokens=2,
                     now=0.2)
    assert ref["ok"] is False and ref["reason"] == "draining"
    # a cancelled drain (the raced-capacity yield) resumes admission
    assert tr.request("resume")["ok"]
    ok = tr.request("submit", rid=9, prompt=[1], max_new_tokens=2,
                    now=0.3)
    assert ok["ok"] is True and len(sched.submitted) == 1
    # a handler exception is classified, never kills the replica
    bad = tr.request("submit", rid="not-an-int", prompt=[1],
                     max_new_tokens=2, now=0.4)
    assert bad["ok"] is False and "error" in bad
    assert tr.request("tick", now=0.5, tick=0)["ok"]
    tr.request("stop")
    t.join(timeout=5.0)
    tr.close()


def test_transport_gives_up_after_max_attempts(tmp_path):
    # nobody on the other end: every attempt times out, the LAST
    # classified error surfaces
    c2p_r, _c2p_w = _pipe_pair()
    _p2c_r, p2c_w = _pipe_pair()
    tr = tp.ReplicaTransport(c2p_r, p2c_w, timeout_s=0.05,
                             max_attempts=2)
    with pytest.raises(tp.TransportTimeout):
        tr.request("tick", now=0.0, tick=0)
    assert tr.timeouts == 2 and tr.retransmits == 1
    tr.close()


# ---------------------------------------------------------------------------
# binary frames + the blob channel (ISSUE 18)
# ---------------------------------------------------------------------------

def test_binary_frame_roundtrip_and_interleaving():
    rf, wf = _pipe_pair()
    payload = bytes(range(256)) * 5
    tp.write_frame(wf, {"seq": 1, "nblobs": 1})
    tp.write_binary_frame(wf, payload)
    tp.write_frame(wf, {"seq": 2})
    reader = tp.FrameReader(rf)
    assert reader.read_frame(timeout_s=1.0) == {"seq": 1, "nblobs": 1}
    assert reader.read_binary_frame(timeout_s=1.0) == payload
    # the stream stays in sync: the next JSON frame parses normally
    assert reader.read_frame(timeout_s=1.0) == {"seq": 2}
    # empty payload is legal (a zero-block handoff edge)
    tp.write_binary_frame(wf, b"")
    assert reader.read_binary_frame(timeout_s=1.0) == b""
    rf.close(), wf.close()


def test_binary_frame_corruption_classified_not_desynced():
    import struct
    # CRC mismatch: flip a payload byte after encoding
    rf, wf = _pipe_pair()
    frame = bytearray(tp.encode_binary_frame(b"hello-kv-pages"))
    frame[-1] ^= 0xFF
    wf.write(bytes(frame))
    tp.write_frame(wf, {"seq": 9})
    wf.flush()
    reader = tp.FrameReader(rf)
    with pytest.raises(tp.TransportCorrupt, match="checksum"):
        reader.read_binary_frame(timeout_s=1.0)
    # the WHOLE corrupt frame was consumed — sync survives
    assert reader.read_frame(timeout_s=1.0) == {"seq": 9}
    rf.close(), wf.close()
    # a binary frame where a message was expected is corruption, not
    # a crash (and vice versa)
    rf, wf = _pipe_pair()
    tp.write_binary_frame(wf, b"pages")
    with pytest.raises(tp.TransportCorrupt, match="unexpected binary"):
        tp.FrameReader(rf).read_frame(timeout_s=1.0)
    rf.close(), wf.close()
    rf, wf = _pipe_pair()
    tp.write_frame(wf, {"seq": 1})
    with pytest.raises(tp.TransportCorrupt, match="expected binary"):
        tp.FrameReader(rf).read_binary_frame(timeout_s=1.0)
    rf.close(), wf.close()
    # absurd binary length (flag set, body over the cap)
    rf, wf = _pipe_pair()
    wf.write(struct.pack(">I", (tp.MAX_FRAME_BYTES + 5)
                         | tp.BINARY_FLAG))
    wf.flush()
    with pytest.raises(tp.TransportCorrupt):
        tp.FrameReader(rf).read_binary_frame(timeout_s=1.0)
    rf.close(), wf.close()


def test_binary_frame_truncation_is_timeout_then_closed():
    # truncated payload, writer still alive: a TIMEOUT (the bytes may
    # still come) with the partial data buffered — completing the
    # frame later succeeds
    rf, wf = _pipe_pair()
    frame = tp.encode_binary_frame(b"0123456789abcdef")
    wf.write(frame[:10])
    wf.flush()
    reader = tp.FrameReader(rf)
    with pytest.raises(tp.TransportTimeout):
        reader.read_binary_frame(timeout_s=0.05)
    wf.write(frame[10:])
    wf.flush()
    assert reader.read_binary_frame(timeout_s=1.0) == b"0123456789abcdef"
    # truncated payload then EOF: CLOSED (the bytes can never come)
    rf2, wf2 = _pipe_pair()
    wf2.write(frame[:10])
    wf2.flush()
    wf2.close()
    with pytest.raises(tp.TransportClosed):
        tp.FrameReader(rf2).read_binary_frame(timeout_s=1.0)
    rf.close(), wf.close(), rf2.close()


def test_serve_loop_blobs_ride_requests_and_dedupe_replay(tmp_path):
    """The blob channel end-to-end over the loopback fakes: payloads
    ride a request (consumed even by ops that refuse), a retransmit
    resends message + payloads and the child's cached-reply replay
    still consumes them — the stream NEVER desyncs."""
    tr, eng, sched, t = _loopback(str(tmp_path))
    tr.request("hello", now=0.0)
    # an op the fakes cannot adopt: the rid-unknown adopt path refuses
    # via the handler-exception classifier, but the blobs were consumed
    # (next request round-trips cleanly)
    r = tr.request("adopt", rid=1, meta={"rid": 1}, now=0.1,
                   blobs=[b"\x00" * 64, b"\x11" * 64])
    assert r["ok"] is False
    assert tr.request("tick", now=0.2, tick=0)["ok"]
    assert sched.steps == 1
    # lost reply on a blob-carrying request: the retransmit resends the
    # payloads; the child replays the cached reply and consumes them —
    # the follow-up tick still parses (sync proof) and no double-work
    r = tr.request("adopt", rid=2, meta={"rid": 2}, now=0.3,
                   blobs=[b"\x22" * 32], inject_drop_reply=True)
    assert r["ok"] is False and tr.retransmits >= 1
    assert tr.request("tick", now=0.4, tick=1)["ok"]
    assert sched.steps == 2
    tr.request("stop")
    t.join(timeout=5.0)
    tr.close()


def test_socket_transport_same_protocol_over_tcp(tmp_path):
    """The socket seams (ISSUE 18): listen/connect/accept on loopback,
    the SAME serve_loop + ReplicaTransport protocol over TCP — dedupe,
    injected reply loss, and blob payloads all behave exactly as over
    pipes."""
    srv = tp.listen()
    host, port = srv.getsockname()
    client = tp.connect(host, port, timeout_s=5.0)
    server_sock, _ = tp.accept_connection(srv, timeout_s=5.0)
    srv.close()
    eng, sched = _FakeEngine(), _FakeScheduler()
    t = threading.Thread(
        target=serve_loop,
        args=(tp.SocketFrameReader(server_sock),
              tp.SocketWriter(server_sock)),
        kwargs=dict(engine=eng, sched=sched, buf=EventBuffer(),
                    clock=SettableClock(), root=str(tmp_path),
                    replica_id=0),
        daemon=True)
    t.start()
    tr = tp.ReplicaTransport(tp.SocketFrameReader(client),
                             tp.SocketWriter(client), timeout_s=0.5)
    hello = tr.request("hello", now=0.0)
    assert hello["ok"] and hello["context_width"] == W
    reply = tr.request("tick", now=0.1, tick=0, inject_drop_reply=True)
    assert reply["ok"] and sched.steps == 1
    assert tr.timeouts == 1 and tr.retransmits == 1
    a = tr.request("submit", rid=5, prompt=[1, 2], max_new_tokens=3,
                   now=0.2)
    b = tr.request("submit", rid=5, prompt=[1, 2], max_new_tokens=3,
                   now=0.2)
    assert a["ok"] and not a["duplicate"] and b["duplicate"]
    r = tr.request("adopt", rid=7, meta={"rid": 7}, now=0.3,
                   blobs=[b"\x33" * 48])
    assert r["ok"] is False                 # fakes can't adopt; sync ok
    assert tr.request("tick", now=0.4, tick=1)["ok"]
    tr.request("stop")
    t.join(timeout=5.0)
    assert not t.is_alive()
    client.close(), server_sock.close()


def test_connect_refused_then_accept_timeout_classified():
    # nobody listening: bounded retry then TransportClosed
    with pytest.raises(tp.TransportClosed):
        tp.connect("127.0.0.1", 1, timeout_s=0.2, retry_interval_s=0.05)
    # nobody dialing: accept classified as TransportTimeout
    srv = tp.listen()
    with pytest.raises(tp.TransportTimeout):
        tp.accept_connection(srv, timeout_s=0.1)
    srv.close()


# ---------------------------------------------------------------------------
# variables npz round-trip
# ---------------------------------------------------------------------------

def test_variables_npz_roundtrip(tmp_path):
    vs = {"params": {"m": {"w": np.arange(6, dtype=np.float32
                                          ).reshape(2, 3),
                           "b": np.zeros((3,), np.float32)},
                     "emb": {"table": np.ones((4, 2), np.float32)}}}
    path = str(tmp_path / "vars.npz")
    save_variables_npz(path, vs)
    back = load_variables_npz(path)
    assert set(back["params"]) == {"m", "emb"}
    np.testing.assert_array_equal(back["params"]["m"]["w"],
                                  vs["params"]["m"]["w"])
    np.testing.assert_array_equal(back["params"]["emb"]["table"],
                                  vs["params"]["emb"]["table"])


# ---------------------------------------------------------------------------
# end to end: one REAL subprocess replica behind the fleet
# ---------------------------------------------------------------------------

def test_process_replica_serves_oracle_tokens_end_to_end():
    """A single process-mode replica (a real child: own jax runtime,
    own engine, heartbeats through the shared files, submit/complete
    over the transport) is semantically invisible — every request's
    tokens equal the greedy full-forward oracle computed in THIS
    process, and the child's own stats probe shows zero leaks and
    pinned compile counts."""
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.serve import ServingFleet, SimClock

    model = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                          ffn_hidden=64, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))
    clock = SimClock()
    fleet = ServingFleet.from_model(
        model, vs, 1, engine_kwargs=dict(max_slots=2, block_size=4),
        replica_mode="process", clock=clock, heartbeat_timeout_s=0.25,
        est_tick_s=0.1, transport_timeout_s=5.0,
        root=tempfile.mkdtemp(prefix="paddle_tpu_proc_test_"))
    try:
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(1, V, rng.randint(2, 6)))
                   for _ in range(3)]
        frs = [fleet.submit(p, 4) for p in prompts]
        for _ in range(200):
            if not fleet.outstanding():
                break
            fleet.tick()
            clock.advance(0.1)
        assert all(fr.finish_reason == "length" for fr in frs)

        fwd = jax.jit(lambda v, i: model.apply(v, i))

        def oracle(prompt, n_new):
            seq, out = list(prompt), []
            for _ in range(n_new):
                pad = np.zeros((1, W), np.int32)
                pad[0, :len(seq)] = seq
                logits = fwd(vs, jnp.asarray(pad))
                out.append(int(np.argmax(
                    np.asarray(logits[0, len(seq) - 1]))))
                seq.append(out[-1])
            return out

        for p, fr in zip(prompts, frs):
            assert fr.tokens == oracle(p, 4)
        probe = fleet.workers[0].stats_probe(clock())
        assert probe is not None
        assert probe["free_blocks"] == probe["num_blocks"] - 1
        assert probe["compile_counts"] == {"prefill": 1, "tick": 1}
        assert fleet.stats()["replica_mode"] == "process"
    finally:
        fleet.shutdown()
    # shutdown reaped the child
    assert fleet.workers[0].transport.proc.poll() is not None
