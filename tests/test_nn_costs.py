"""Cost function tests vs numpy oracles (analog of the reference's
CostLayer gradient tests in test_LayerGrad.cpp)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.nn import costs


def test_softmax_ce_matches_numpy(rng):
    logits = jax.random.normal(rng, (6, 5))
    labels = jnp.array([0, 1, 2, 3, 4, -1])
    l = np.asarray(costs.softmax_cross_entropy(logits, labels))
    ln = np.asarray(logits)
    p = np.exp(ln - ln.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    for i in range(5):
        np.testing.assert_allclose(l[i], -np.log(p[i, i]), rtol=1e-5)
    assert l[5] == 0.0  # masked


def test_ce_grad_is_softmax_minus_onehot(rng):
    logits = jax.random.normal(rng, (4, 3))
    labels = jnp.array([0, 1, 2, 0])
    g = jax.grad(lambda z: costs.softmax_cross_entropy(z, labels).sum())(logits)
    p = np.asarray(jax.nn.softmax(logits))
    onehot = np.eye(3)[np.asarray(labels)]
    np.testing.assert_allclose(np.asarray(g), p - onehot, atol=1e-5)


def test_mse_and_smooth_l1():
    o = jnp.array([[1.0, 2.0]])
    t = jnp.array([[0.0, 0.0]])
    np.testing.assert_allclose(np.asarray(costs.mse(o, t)), [2.5])
    np.testing.assert_allclose(np.asarray(costs.smooth_l1(o, t)), [0.5 + 1.5])


def test_rank_cost_symmetry():
    l = jnp.array([[2.0]])
    r = jnp.array([[1.0]])
    c1 = float(costs.rank_cost(l, r, jnp.array([1.0]))[0])
    c2 = float(costs.rank_cost(l, r, jnp.array([0.0]))[0])
    assert c1 < c2  # correct order is cheaper


def test_multi_binary_ce_matches_sigmoid_oracle(rng):
    x = jax.random.normal(rng, (3, 4))
    t = (jax.random.uniform(rng, (3, 4)) > 0.5).astype(jnp.float32)
    got = np.asarray(costs.multi_binary_ce(x, t))
    p = 1 / (1 + np.exp(-np.asarray(x)))
    want = -(np.asarray(t) * np.log(p) + (1 - np.asarray(t)) * np.log(1 - p))
    np.testing.assert_allclose(got, want.sum(-1), rtol=1e-4)


def test_huber_classification_regions():
    s = jnp.array([[2.0], [0.5], [-2.0]])
    y = jnp.array([1.0, 1.0, 1.0])
    l = np.asarray(costs.huber_classification(s, y))
    assert l[0] == 0.0
    np.testing.assert_allclose(l[1], 0.25)
    np.testing.assert_allclose(l[2], 8.0)


def test_hinge():
    s = jnp.array([[0.5], [-0.5]])
    l = np.asarray(costs.hinge(s, jnp.array([1.0, 1.0])))
    np.testing.assert_allclose(l, [0.5, 1.5])


def test_nce_decreases_for_true_class(rng):
    # loss should be lower when hidden aligns with the true class embedding
    V, D = 8, 4
    w = jax.random.normal(rng, (V, D))
    b = jnp.zeros((V,))
    labels = jnp.array([2])
    noise = jnp.array([[5, 6, 7]])
    h_good = w[2][None, :] * 3
    h_bad = -w[2][None, :] * 3
    assert float(costs.nce_loss(h_good, labels, w, b, noise)[0]) < \
        float(costs.nce_loss(h_bad, labels, w, b, noise)[0])


def test_hsigmoid_codes_and_loss(rng):
    C = 8
    labels = jnp.array([0, 3, 7])
    codes, signs = costs.build_hsigmoid_codes(labels, C)
    assert codes.shape == (3, 3)
    # all internal nodes in range
    assert int(codes.max()) < C - 1 or int(codes.max()) < C
    w = jax.random.normal(rng, (C, 4))
    b = jnp.zeros((C,))
    h = jax.random.normal(rng, (3, 4))
    l = costs.hsigmoid_loss(h, labels, codes, signs, w, b)
    assert l.shape == (3,)
    assert (np.asarray(l) > 0).all()
    # gradient flows
    g = jax.grad(lambda hh: costs.hsigmoid_loss(hh, labels, codes, signs,
                                                w, b).sum())(h)
    assert np.abs(np.asarray(g)).sum() > 0


def test_lambda_rank_prefers_correct_order():
    r = jnp.array([[3.0, 2.0, 1.0, 0.0]])
    good = jnp.array([[4.0, 3.0, 2.0, 1.0]])
    bad = jnp.array([[1.0, 2.0, 3.0, 4.0]])
    lg = float(costs.lambda_rank_ndcg(good, r)[0])
    lb = float(costs.lambda_rank_ndcg(bad, r)[0])
    assert lg < lb


def test_lambda_rank_no_overflow_on_extreme_scores():
    """Strongly mis-ordered pairs (sigma*diff < -88) must stay finite — the
    logistic term uses softplus, not log1p(exp(.))."""
    r = jnp.array([[3.0, 0.0]])
    s = jnp.array([[-200.0, 200.0]])
    loss = costs.lambda_rank_ndcg(s, r)
    assert np.isfinite(np.asarray(loss)).all()
    g = jax.grad(lambda ss: costs.lambda_rank_ndcg(ss, r).sum())(s)
    assert np.isfinite(np.asarray(g)).all()


def test_reduce_masked():
    x = jnp.array([1.0, 2.0, 3.0])
    m = jnp.array([1.0, 1.0, 0.0])
    assert float(costs.reduce(x, m)) == 1.5
    assert float(costs.reduce(x, how="sum")) == 6.0
