"""Prefill/decode disaggregation tests (ISSUE 18): role-split fleets
must be a pure PLACEMENT change — prefill on replica A + decode on
replica B produces bit-identical greedy tokens to colocated serving,
across ragged lengths, int8-quantized KV, and CoW-shared session
prefixes; a prefill replica killed mid-stream degrades to the ordinary
dead-replica resubmit (exactly one terminal record per rid); the wire
cost of every handoff is accounted to the byte; and the role-aware
router, the hostile-scale loadgen, the router_ms host-cost meter and
the M/M/c Erlang-C term each hold their contracts.

Everything in-process on a :class:`SimClock` except where noted — the
socket path is exercised end-to-end by tests/test_transport.py and the
bench disagg leg."""

import collections
import tempfile

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models import TransformerLM
from paddle_tpu.obs import (InMemorySink, Telemetry, flow_connected,
                            flow_summary, summarize_handoffs)
from paddle_tpu.serve import ServingFleet, SimClock, erlang_c_wait
from paddle_tpu.serve.loadgen import hostile_workload, workload_stats
from paddle_tpu.train import FaultSchedule

V, W, DIM, LAYERS, HEADS, FFN = 64, 24, 32, 2, 4, 64
BS = 4
HD = DIM // HEADS                         # head_dim = 8
DT, HB = 0.1, 0.25

# exact per-block wire bytes for this geometry: K and V pages, each
# [layers, heads, BS, head_dim] per block
F32_BLOCK = 2 * LAYERS * HEADS * BS * HD * 4
INT8_BLOCK = 2 * LAYERS * HEADS * BS * (HD * 1 + 4)   # values + f32 scales


@pytest.fixture(scope="module")
def model_and_vars():
    model = TransformerLM(vocab=V, dim=DIM, num_layers=LAYERS,
                          num_heads=HEADS, ffn_hidden=FFN, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))
    return model, vs


def _greedy_oracle(model, vs, prompt, n_new):
    fwd = jax.jit(lambda v, i: model.apply(v, i))
    seq, out = list(prompt), []
    for _ in range(n_new):
        pad = np.zeros((1, W), np.int32)
        pad[0, :len(seq)] = seq
        logits = fwd(vs, jnp.asarray(pad))
        tok = int(np.argmax(np.asarray(logits[0, len(seq) - 1])))
        out.append(tok)
        seq.append(tok)
    return out


def _fleet(model, vs, n, *, roles=None, telemetry=None, faults=None,
           engine_kwargs=None, **kw):
    ek = dict(max_slots=2, block_size=BS, num_blocks=24)
    ek.update(engine_kwargs or {})
    return ServingFleet.from_model(
        model, vs, n, engine_kwargs=ek, roles=roles,
        telemetry=telemetry, faults=faults, clock=SimClock(),
        heartbeat_timeout_s=HB, est_tick_s=DT,
        root=tempfile.mkdtemp(prefix="paddle_tpu_disagg_test_"), **kw)


def _run(fleet, jobs, max_ticks=400):
    """Submit (prompt, n_new[, session]) jobs, tick to completion."""
    frs = []
    for job in jobs:
        sid = job[2] if len(job) > 2 else None
        frs.append(fleet.submit(list(job[0]), job[1], session_id=sid))
    for _ in range(max_ticks):
        if not fleet.outstanding():
            break
        fleet.tick()
        fleet.clock.advance(DT)
    assert not fleet.outstanding(), "fleet did not converge"
    return frs


def _ragged_jobs(nprng, n=8, sessions=False):
    jobs = []
    for i in range(n):
        plen = int(nprng.randint(1, 9))           # ragged 1..8
        n_new = int(nprng.randint(2, 7))
        prompt = list(nprng.randint(1, V, plen))
        if sessions and i % 2 == 1:
            # share the previous job's prompt as a prefix (CoW path)
            prev = jobs[-1][0]
            prompt = list(prev) + prompt[: max(1, 8 - len(prev))]
            jobs.append((prompt, n_new, jobs[-1][2]))
        else:
            jobs.append((prompt, n_new, i))
    return jobs


# ---------------------------------------------------------------------------
# token identity: disaggregation is a placement change, not a math change
# ---------------------------------------------------------------------------

def test_disagg_token_identity_vs_colocated_ragged(model_and_vars,
                                                   nprng):
    model, vs = model_and_vars
    jobs = _ragged_jobs(nprng, n=8, sessions=True)
    colo = _run(_fleet(model, vs, 3), jobs)
    dis_fleet = _fleet(model, vs, 3, roles=["prefill", "decode",
                                            "decode"])
    dis = _run(dis_fleet, jobs)
    assert all(fr.finish_reason == "length" for fr in colo + dis)
    for a, b in zip(colo, dis):
        assert a.tokens == b.tokens, (a.rid, a.tokens, b.tokens)
        assert b.tokens == _greedy_oracle(model, vs, b.prompt,
                                          b.max_new_tokens)
    # every request actually crossed the prefill -> decode boundary
    assert dis_fleet.handoff_count == len(jobs)
    # wire accounting is exact: bytes == blocks x per-block f32 bytes
    assert dis_fleet.handoff_wire_bytes == \
        dis_fleet.handoff_blocks * F32_BLOCK
    assert dis_fleet.stale_handoffs == 0
    # no replica leaked KV blocks through the export/adopt cycle
    for w in dis_fleet.workers:
        cache = w.engine.cache
        assert cache.free_blocks == cache.num_blocks - 1, w.replica_id


def test_disagg_int8_identity_and_wire_ratio(model_and_vars, nprng):
    """Quantized KV crosses the wire quantized: int8 disagg matches
    int8 colocated token-for-token, and the measured bytes-per-block
    ratio vs f32 is the analytic (hd*4)/(hd+4) ~ 2.7x (ISSUE 18)."""
    model, vs = model_and_vars
    ek = dict(kv_dtype="int8")
    jobs = _ragged_jobs(nprng, n=6)
    colo = _run(_fleet(model, vs, 2, engine_kwargs=ek), jobs)
    q = _fleet(model, vs, 3, roles=["prefill", "decode", "decode"],
               engine_kwargs=ek)
    dis = _run(q, jobs)
    for a, b in zip(colo, dis):
        assert a.finish_reason == b.finish_reason == "length"
        assert a.tokens == b.tokens, (a.rid, a.tokens, b.tokens)
    assert q.handoff_count == len(jobs)
    assert q.handoff_wire_bytes == q.handoff_blocks * INT8_BLOCK
    ratio = F32_BLOCK / (q.handoff_wire_bytes / q.handoff_blocks)
    assert ratio == pytest.approx((HD * 4) / (HD + 4))   # 2.67x for hd=8
    assert ratio > 2.5


# ---------------------------------------------------------------------------
# role-aware routing + handoff telemetry
# ---------------------------------------------------------------------------

def test_disagg_routing_telemetry_and_connected_flow(model_and_vars,
                                                     nprng):
    model, vs = model_and_vars
    mem = InMemorySink()
    fleet = _fleet(model, vs, 3, roles=["prefill", "decode", "decode"],
                   telemetry=Telemetry(sinks=[mem]), trace=True)
    jobs = _ragged_jobs(nprng, n=6)
    frs = _run(fleet, jobs)
    assert all(fr.finish_reason == "length" for fr in frs)
    # role-aware placement: every request prefills on the prefill
    # replica and terminates on a decode replica
    for fr in frs:
        assert fr.attempts[0] == 0, fr.attempts
        assert fr.attempts[-1] in (1, 2), fr.attempts
        assert fr.replica in (1, 2)
    # per-handoff telemetry: one kv_handoff record per request with the
    # full schema, aggregable by obs.summarize_handoffs
    hos = mem.by_kind("kv_handoff")
    assert len(hos) == len(jobs)
    for h in hos:
        assert h["src_replica"] == 0 and h["dst_replica"] in (1, 2)
        assert h["blocks"] >= 1 and h["wire_bytes"] > 0
        assert h["quant"] == "float32" and h["transfer_ms"] >= 0.0
    agg = summarize_handoffs(mem.records)
    assert agg["handoffs"] == len(jobs)
    assert agg["wire_bytes"] == fleet.handoff_wire_bytes
    assert agg["mean_blocks"] == pytest.approx(
        fleet.handoff_blocks / len(jobs), abs=0.01)
    assert agg["by_quant"] == {"float32": len(jobs)}
    # the run report carries the block
    from paddle_tpu.obs.report import format_summary, summarize
    summ = summarize(mem.records)
    assert summ["serving"]["handoffs"]["handoffs"] == len(jobs)
    assert "kv handoffs" in format_summary(summ)
    # the merged trace: each rid's flow is connected THROUGH the
    # kv_handoff span — prefill lane -> router handoff -> decode lane
    tr = fleet.fleet_trace()
    names = {e["name"] for e in tr["traceEvents"] if e.get("ph") == "X"}
    assert "kv_handoff" in names, names
    for fr in frs:
        assert flow_connected(tr, fr.rid), flow_summary(tr).get(fr.rid)
        pids = {pid for _, pid in flow_summary(tr)[fr.rid]}
        assert len(pids) >= 2, (fr.rid, pids)    # crossed lanes


# ---------------------------------------------------------------------------
# the death drill: prefill dies mid-stream
# ---------------------------------------------------------------------------

def test_disagg_prefill_death_rehomes_with_one_terminal(model_and_vars,
                                                        nprng):
    """Kill a prefill replica while its requests are in flight: the
    in-progress work re-homes to the surviving prefill replica, every
    request still reaches exactly one terminal record with oracle
    tokens, and any handoff caught mid-transfer is accounted (stale or
    re-driven), never double-decoded."""
    model, vs = model_and_vars
    mem = InMemorySink()
    faults = FaultSchedule(kill_replica_at_tick=(1, 0))
    fleet = _fleet(model, vs, 3,
                   roles=["prefill", "prefill", "decode"],
                   telemetry=Telemetry(sinks=[mem]), faults=faults)
    jobs = [(list(nprng.randint(1, V, 4)), 6, None) for _ in range(6)]
    frs = _run(fleet, jobs)
    assert all(fr.finish_reason == "length" for fr in frs)
    assert any(fr.retries > 0 and 0 in fr.attempts for fr in frs), \
        "the kill must catch at least one request on replica 0"
    for fr in frs:
        assert fr.tokens == _greedy_oracle(model, vs, fr.prompt,
                                           fr.max_new_tokens)
        assert fr.replica == 2                   # decoded on the decoder
    # exactly one terminal record per rid (retried lineage intact)
    by_rid = collections.defaultdict(list)
    for r in mem.by_kind("request"):
        by_rid[r["rid"]].append(r)
    for fr in frs:
        terminal = [r for r in by_rid[fr.rid]
                    if r["finish_reason"] != "retried"]
        assert len(terminal) == 1, (fr.rid, by_rid[fr.rid])
        assert terminal[0]["finish_reason"] == "length"
    assert fleet.handoff_count >= len(jobs)      # re-homed ones re-ship
    assert not fleet._pending_handoffs
    for w in fleet.workers:
        if w.replica_id == 0:
            continue
        cache = w.engine.cache
        assert cache.free_blocks == cache.num_blocks - 1, w.replica_id


# ---------------------------------------------------------------------------
# hostile-scale loadgen + the router_ms host-cost meter
# ---------------------------------------------------------------------------

def test_hostile_workload_rate_and_router_cost_meter(model_and_vars):
    model, vs = model_and_vars
    wl = hostile_workload(400, V, max_total=W)
    stats = workload_stats(wl)
    # the hostile preset is genuinely hostile: >= 10k requests/sec of
    # sim-time arrivals, bursty
    span = wl[-1].at_s - wl[0].at_s
    assert span > 0 and len(wl) / span >= 10_000.0, len(wl) / span
    assert stats["n"] == 400
    same = hostile_workload(400, V, max_total=W)
    assert [(g.at_s, g.prompt) for g in wl] == \
        [(g.at_s, g.prompt) for g in same]       # seeded
    # drive a small slice through a disagg fleet and read the meter:
    # router_ms is HOST wall time (perf_counter), present and sane even
    # though the fleet runs on a SimClock
    fleet = _fleet(model, vs, 3, roles=["prefill", "decode", "decode"])
    frs = _run(fleet, [(g.prompt, min(g.max_new_tokens, 4), g.session_id)
                       for g in wl[:40]])
    assert all(fr.finish_reason in ("length", "eos") for fr in frs)
    rm = fleet.stats()["router_ms"]
    assert set(rm) == {"total", "per_tick_mean", "per_tick_max", "ticks"}
    assert rm["ticks"] == fleet.ticks > 0
    assert rm["total"] > 0.0
    assert rm["per_tick_max"] >= rm["per_tick_mean"] > 0.0
    assert rm["total"] == pytest.approx(
        rm["per_tick_mean"] * rm["ticks"], rel=1e-6)


# ---------------------------------------------------------------------------
# the M/M/c term
# ---------------------------------------------------------------------------

def test_erlang_c_wait_units_and_limits():
    # empty / degenerate systems wait zero
    assert erlang_c_wait(0.0, 10.0, 4) == 0.0
    assert erlang_c_wait(5.0, 0.0, 4) == 0.0
    assert erlang_c_wait(5.0, 10.0, 0) == 0.0
    # at or past saturation the wait is unbounded
    assert erlang_c_wait(10.0, 10.0, 1) == float("inf")
    assert erlang_c_wait(45.0, 10.0, 4) == float("inf")
    # M/M/1 closed form: Wq = rho / (mu - lam)
    lam, mu = 6.0, 10.0
    assert erlang_c_wait(lam, mu, 1) == pytest.approx(
        (lam / mu) / (mu - lam))
    # monotone in offered load, relieved by capacity
    w2 = erlang_c_wait(8.0, 10.0, 2)
    assert 0.0 < erlang_c_wait(4.0, 10.0, 2) < w2
    assert erlang_c_wait(8.0, 10.0, 4) < w2
