"""plotcurve analog (reference ``python/paddle/utils/plotcurve.py``):
parse trainer progress lines, render a figure (or plain table headless)."""

import io

from paddle_tpu.utils import plotcurve


LOG = """\
INFO paddle_tpu.trainer pass 0 batch 100 cost=0.6931 error=0.5000
some unrelated line
INFO paddle_tpu.trainer pass 0 batch 200 cost=0.5122 error=0.4100
INFO paddle_tpu.trainer pass 1 batch 100 cost=0.3301 error=0.2500
"""


def test_parse_log_extracts_series():
    series = plotcurve.parse_log(LOG.splitlines(), ["cost", "error"])
    assert [v for _, v in series["cost"]] == [0.6931, 0.5122, 0.3301]
    assert [v for _, v in series["error"]] == [0.5, 0.41, 0.25]
    # x is cumulative across passes (batch counters reset per pass)
    assert [x for x, _ in series["cost"]] == [0, 1, 2]


def test_parse_log_missing_key_is_empty():
    series = plotcurve.parse_log(LOG.splitlines(), ["nope"])
    assert series["nope"] == []


def test_plot_curves_writes_output(tmp_path):
    series = plotcurve.parse_log(LOG.splitlines(), ["cost"])
    out = tmp_path / "curve.png"
    kind = plotcurve.plot_curves(series, str(out))
    assert kind in ("figure", "table")
    assert out.exists() and out.stat().st_size > 0


def test_table_fallback_handles_binary_stream_and_keeps_it_open(monkeypatch):
    """Without matplotlib the fallback must write the plain table to the
    caller's stream — including a BINARY one like sys.stdout.buffer (the
    CLI default) — and must not close a caller-provided stream."""
    import builtins
    real_import = builtins.__import__

    def no_matplotlib(name, *a, **k):
        if name.startswith("matplotlib"):
            raise ImportError(name)
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_matplotlib)
    series = {"cost": [(0, 1.0), (1, 0.5)]}
    buf = io.BytesIO()
    kind = plotcurve.plot_curves(series, buf)
    assert kind == "table"
    assert not buf.closed
    assert buf.getvalue().startswith(b"# x cost")


def test_cli_roundtrip(tmp_path, capsys):
    log = tmp_path / "train.log"
    log.write_text(LOG)
    out = tmp_path / "fig.png"
    plotcurve.main(["-i", str(log), "-o", str(out), "cost", "error"])
    assert out.exists() and out.stat().st_size > 0
