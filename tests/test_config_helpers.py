"""The v1-style declarative frontend: DSL-built networks must train, match
their imperative equivalents, and round-trip through the model IR (the
reference's config-pair equivalence tests, ``test_CompareTwoNets.cpp`` /
``test_NetworkCompare.cpp``)."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu.config_helpers as H
from paddle_tpu.core.config import (build_module, config_from_json,
                                    config_to_json, module_config)
from paddle_tpu.nn.layers import Linear


def test_dsl_mlp_matches_imperative():
    img = H.data_layer("image")
    h = H.fc_layer(img, size=16, act="relu")
    out = H.fc_layer(h, size=4)
    net = H.build_network(out)

    x = jnp.asarray(np.random.RandomState(0).normal(
        size=(3, 8)).astype(np.float32))
    params = net.init(jax.random.PRNGKey(0), x)
    y = net.apply(params, x)
    assert y.shape == (3, 4)

    # same weights applied functionally give the same answer
    tree = params["params"]["network"]
    mods = list(tree)
    w1, b1 = tree[mods[0]]["w"], tree[mods[0]]["b"]
    w2, b2 = tree[mods[1]]["w"], tree[mods[1]]["b"]
    want = jnp.maximum(x @ w1 + b1, 0) @ w2 + b2
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=1e-5)


def test_dsl_network_ir_roundtrip():
    a = H.data_layer("a")
    b = H.data_layer("b")
    ha = H.fc_layer(a, size=8, act="tanh")
    hb = H.fc_layer(b, size=8, act="tanh")
    merged = H.addto_layer([ha, hb], act="relu")
    sim = H.cos_sim(ha, hb)
    net = H.build_network(merged, sim)

    x = jnp.ones((2, 5))
    y = jnp.ones((2, 5)) * 0.5
    params = net.init(jax.random.PRNGKey(0), x, y)
    o1 = net.apply(params, x, y)
    cfg = config_from_json(config_to_json(module_config(net)))
    net2 = build_module(cfg, trusted=False)
    o2 = net2.apply(params, x, y)
    for u, v in zip(o1, o2):
        np.testing.assert_allclose(np.asarray(u), np.asarray(v), rtol=1e-6)


def test_dsl_conv_pool_and_sequence_helpers():
    img = H.data_layer("image")
    feat = H.simple_img_conv_pool(img, filter_size=3, num_filters=4,
                                  pool_size=2)
    net = H.build_network(feat)
    x = jnp.ones((2, 8, 8, 1))
    p = net.init(jax.random.PRNGKey(0), x)
    y = net.apply(p, x)
    assert y.shape == (2, 4, 4, 4)

    seqs = H.data_layer("tokens")
    lens = H.data_layer("lengths")
    emb = H.embedding_layer(seqs, size=6, vocab=20)
    rnn = H.lstmemory(emb, size=5)
    last = H.last_seq(rnn, lens)
    net2 = H.build_network(last)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 20, (3, 7)))
    lengths = jnp.asarray([7, 3, 5])
    p2 = net2.init(jax.random.PRNGKey(1), toks, lengths)
    out = net2.apply(p2, toks, lengths)
    assert out.shape == (3, 5)


def test_dsl_trains_end_to_end():
    from paddle_tpu import optim
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    img = H.data_layer("x")
    h = H.fc_layer(img, size=32, act="relu")
    out = H.fc_layer(h, size=2)
    net = H.build_network(out)

    rng = np.random.RandomState(0)
    xs = rng.normal(size=(256, 8)).astype(np.float32)
    ys = (xs.sum(-1) > 0).astype(np.int32)
    batches = [{"x": xs[i:i + 32], "label": ys[i:i + 32]}
               for i in range(0, 256, 32)]
    tr = Trainer(net, lambda o, b: costs.softmax_cross_entropy(o, b["label"]),
                 optim.adam(1e-2))
    tr.init(jax.random.PRNGKey(0), batches[0])
    from paddle_tpu.train.evaluators import ClassificationError
    tr.evaluator = ClassificationError()
    tr.train(lambda: iter(batches), num_passes=20, log_period=0)
    _, metrics = tr.evaluate(lambda: iter(batches))
    assert metrics["accuracy"] > 0.9, metrics


def test_batch_norm_layer_with_act():
    img = H.data_layer("x")
    h = H.fc_layer(img, size=8)
    bn = H.batch_norm_layer(h, act="relu")
    net = H.build_network(bn)
    x = jnp.asarray(np.random.RandomState(0).normal(
        size=(4, 6)).astype(np.float32))
    p = net.init(jax.random.PRNGKey(0), x)
    y, _ = net.apply(p, x, train=True, mutable=("state",))
    assert (np.asarray(y) >= 0).all()


def test_abandoned_graph_does_not_leak():
    H.data_layer("junk")           # abandoned script
    H.reset_graph()
    a = H.data_layer("x")
    net = H.build_network(H.fc_layer(a, size=2))
    assert sum(m is None for m in net.modules) == 1

    # build_network itself also resets: a failed script then a new one
    H.data_layer("junk2")
    b = H.data_layer("y")          # same (leaked) graph...
    net2 = H.build_network(H.fc_layer(b, size=2))
    # ...but after this build, the next script starts clean
    c = H.data_layer("z")
    net3 = H.build_network(H.fc_layer(c, size=2))
    assert sum(m is None for m in net3.modules) == 1


def test_surplus_inputs_rejected():
    a = H.data_layer("x")
    net = H.build_network(H.fc_layer(a, size=2))
    x = jnp.ones((2, 3))
    p = net.init(jax.random.PRNGKey(0), x)
    import pytest
    with pytest.raises(ValueError, match="surplus"):
        net.apply(p, x, x)


def test_graph_scope_isolates_failures():
    """An exception inside graph_scope must not leak half-built nodes into
    the next config script (ADVICE r2)."""
    import pytest
    with pytest.raises(RuntimeError):
        with H.graph_scope():
            H.data_layer("junk")
            raise RuntimeError("config script blew up")
    a = H.data_layer("x")
    net = H.build_network(H.fc_layer(a, size=2))
    assert sum(m is None for m in net.modules) == 1


def test_graph_scope_nested_outer_survives():
    outer = H.data_layer("x")
    with H.graph_scope():
        b = H.data_layer("inner")
        inner_net = H.build_network(H.fc_layer(b, size=2))
    net = H.build_network(H.fc_layer(outer, size=3))
    assert sum(m is None for m in inner_net.modules) == 1
    assert sum(m is None for m in net.modules) == 1


def test_thin_wrapper_surface_builds_and_runs():
    """The widened wrapper set: a net touching many of the thin DSL
    wrappers builds, initializes, and runs."""
    x = H.data_layer("x")
    h = H.fc_layer(x, size=12, act="relu")
    h = H.layer_norm_layer(h)
    h = H.maxout_layer(h, groups=3)            # 12 -> 4
    h = H.bias_layer(h)
    h = H.scale_shift_layer(h)
    h = H.slope_intercept_layer(h, 2.0, 0.5)
    h = H.row_l2_norm_layer(h)
    a = H.fc_layer(h, size=4)
    d = H.l2_distance_layer(a, h)
    s = H.sum_to_one_norm_layer(H.fc_layer(h, size=4, act="sigmoid"))
    out = H.concat_layer([s, a])
    net = H.build_network(out)
    xv = jnp.asarray(np.random.RandomState(0).normal(
        size=(3, 8)).astype(np.float32))
    p = net.init(jax.random.PRNGKey(0), xv)
    y = net.apply(p, xv)
    assert y.shape == (3, 8)
    assert np.isfinite(np.asarray(y)).all()


def test_img_wrapper_surface_builds_and_runs():
    img = H.data_layer("img")
    c = H.img_conv_layer(img, 3, 8, act="relu")
    c = H.img_cmrnorm_layer(c, size=3)
    c = H.depthwise_conv_layer(c, 3)
    c = H.pad_layer(c, (1, 1, 1, 1))
    c = H.crop_layer(c, (1, 1), (8, 8))
    c = H.spp_layer(c, levels=2)
    out = H.fc_layer(c, size=5)
    net = H.build_network(out)
    xv = jnp.asarray(np.random.RandomState(0).normal(
        size=(2, 8, 8, 3)).astype(np.float32))
    p = net.init(jax.random.PRNGKey(0), xv)
    y = net.apply(p, xv)
    assert y.shape == (2, 5)


def test_composite_networks_build_and_run():
    """networks.py-tier composites: vgg_16_network (downscaled input),
    simple_lstm/simple_gru, sequence_conv_pool."""
    img = H.data_layer("image")
    logits = H.vgg_16_network(img, num_classes=7, with_bn=False)
    net = H.build_network(logits)
    x = jnp.asarray(np.random.RandomState(0).normal(
        size=(2, 32, 32, 3)).astype(np.float32))
    p = net.init(jax.random.PRNGKey(0), x, train=True)
    y, _ = net.apply(p, x, train=True, mutable=("state",),
                     rngs={"dropout": jax.random.PRNGKey(1)})
    assert y.shape == (2, 7)

    seq = H.data_layer("tokens")
    lengths = H.data_layer("length")
    e = H.embedding_layer(seq, size=16, vocab=50)
    a = H.simple_lstm(e, 12)
    b = H.simple_gru(e, 12)
    c = H.sequence_conv_pool(e, lengths, context_len=3, hidden_size=20)
    last = H.last_seq(H.concat_layer([a, b]), lengths)
    out = H.fc_layer(H.concat_layer([last, c]), size=3)
    net2 = H.build_network(out)
    toks = jnp.asarray(np.random.RandomState(1).randint(0, 50, (2, 9)))
    lens = jnp.asarray(np.array([9, 5], np.int32))
    p2 = net2.init(jax.random.PRNGKey(0), toks, lens)
    y2 = net2.apply(p2, toks, lens)
    assert y2.shape == (2, 3)
    assert np.isfinite(np.asarray(y2)).all()
