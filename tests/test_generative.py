"""GAN + VAE demo convergence tests (the analog of the reference's
``v1_api_demo/{gan,vae}`` acceptance demos, asserting real learning on
small synthetic data)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import optim
from paddle_tpu.models.gan import Discriminator, Generator, gan_step_fn
from paddle_tpu.models.vae import VAE, elbo_loss


def test_gan_learns_shifted_gaussian():
    """2-D GAN (the gan_conf.py task): generator distribution must move to
    the data's mean."""
    rng = np.random.RandomState(0)
    Z, D, B = 8, 2, 64
    target_mean = np.array([2.0, -1.0], np.float32)

    gen = Generator(sample_dim=D, hidden=32, use_bn=False)
    disc = Discriminator(hidden=32)
    key = jax.random.PRNGKey(0)
    g_vars = gen.init(key, jnp.zeros((B, Z)))
    d_vars = disc.init(jax.random.PRNGKey(1), jnp.zeros((B, D)))
    g_vars = {"params": g_vars["params"], "state": g_vars.get("state", {})}
    d_vars = {"params": d_vars["params"], "state": d_vars.get("state", {})}
    g_opt = optim.adam(2e-3)
    d_opt = optim.adam(2e-3)
    g_os, d_os = g_opt.init(g_vars["params"]), d_opt.init(d_vars["params"])
    step = gan_step_fn(gen, disc, g_opt, d_opt)

    sno = jnp.zeros((), jnp.int32)
    for i in range(400):
        real = jnp.asarray(
            rng.normal(size=(B, D)).astype(np.float32) * 0.3 + target_mean)
        noise = jnp.asarray(rng.normal(size=(B, Z)).astype(np.float32))
        g_vars, d_vars, g_os, d_os, d_loss, g_loss = step(
            g_vars, d_vars, g_os, d_os, sno + i, real, noise)

    assert np.isfinite(float(d_loss)) and np.isfinite(float(g_loss))
    noise = jnp.asarray(rng.normal(size=(512, Z)).astype(np.float32))
    fake = gen.apply(g_vars, noise, train=False)
    got_mean = np.asarray(fake).mean(0)
    np.testing.assert_allclose(got_mean, target_mean, atol=0.5)


def test_vae_elbo_decreases_and_reconstructs():
    rng = np.random.RandomState(0)
    D, B = 36, 64
    # two binary prototype patterns + noise
    protos = (rng.uniform(size=(2, D)) > 0.5).astype(np.float32)

    def batch():
        which = rng.randint(0, 2, B)
        x = protos[which]
        flip = rng.uniform(size=x.shape) < 0.02
        return jnp.asarray(np.abs(x - flip.astype(np.float32)))

    vae = VAE(input_dim=D, latent=4, hidden=32)
    x0 = batch()
    variables = vae.init(jax.random.PRNGKey(0), x0,
                         rngs={"params": jax.random.PRNGKey(0),
                               "sample": jax.random.PRNGKey(1)})
    opt = optim.adam(3e-3)
    opt_state = opt.init(variables["params"])

    @jax.jit
    def step(params, opt_state, x, key):
        def loss_fn(p):
            recon, mu, logvar = vae.apply({"params": p}, x,
                                          rngs={"sample": key})
            return elbo_loss(recon, x, mu, logvar)
        loss, g = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.apply(g, opt_state, params, jnp.zeros((), jnp.int32))
        return loss, params, opt_state

    params = variables["params"]
    first = None
    for i in range(300):
        loss, params, opt_state = step(params, opt_state, batch(),
                                       jax.random.PRNGKey(i))
        if first is None:
            first = float(loss)
    assert float(loss) < 0.5 * first, (first, float(loss))

    # reconstruction of a clean prototype should round-trip
    recon, _, _ = vae.apply({"params": params}, jnp.asarray(protos),
                            train=False)
    bits = (np.asarray(jax.nn.sigmoid(recon)) > 0.5).astype(np.float32)
    assert (bits == protos).mean() > 0.95
