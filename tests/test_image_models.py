"""Image model zoo tests: shapes, BN state threading, cifar-ResNet training."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import optim
from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
from paddle_tpu.models import (AlexNet, GoogLeNet, resnet18, resnet50,
                               resnet_cifar, vgg16)
from paddle_tpu.nn import costs
from paddle_tpu.train import Trainer, ClassificationError


def n_params(tree):
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(tree))


def test_resnet50_param_count(rng):
    m = resnet50(num_classes=1000)
    x = jnp.zeros((1, 64, 64, 3))  # small spatial for test speed
    vs = m.init(rng, x, train=True)
    # canonical ResNet-50: ~25.5M params
    n = n_params(vs["params"])
    assert 25_000_000 < n < 26_100_000, n
    out = m.apply(vs, x)
    assert out.shape == (1, 1000)


def test_resnet18_forward_and_bn_state(rng):
    m = resnet18(num_classes=10)
    x = jax.random.normal(rng, (2, 32, 32, 3))
    vs = m.init(rng, x, train=True)
    out, new = m.apply(vs, x, train=True, mutable=("state",))
    assert out.shape == (2, 10)
    # BN means moved
    before = jax.tree_util.tree_leaves(vs["state"])
    after = jax.tree_util.tree_leaves(new["state"])
    moved = sum(float(np.abs(np.asarray(a) - np.asarray(b)).sum())
                for a, b in zip(before, after))
    assert moved > 0


@pytest.mark.parametrize("ctor,shape", [
    (lambda: AlexNet(10), (1, 227, 227, 3)),
    (lambda: vgg16(10), (1, 32, 32, 3)),
    (lambda: GoogLeNet(10), (1, 64, 64, 3)),
])
def test_zoo_forward_shapes(ctor, shape, rng):
    m = ctor()
    x = jnp.zeros(shape)
    vs = m.init(rng, x, train=True)
    assert m.apply(vs, x).shape == (1, 10)


def test_bf16_policy_resnet(rng):
    with use_policy(bfloat16_compute):
        m = resnet_cifar(depth_n=1)
        x = jax.random.normal(rng, (2, 32, 32, 3))
        vs = m.init(rng, x, train=True)
        out = m.apply(vs, x)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_cifar_resnet_trains(rng):
    from paddle_tpu.data import datasets, batched, map_readers
    m = resnet_cifar(depth_n=1)
    tr = Trainer(model=m,
                 loss_fn=lambda o, b: costs.softmax_cross_entropy(o, b["label"]),
                 optimizer=optim.adam(2e-3),
                 evaluator=ClassificationError())
    r = datasets.cifar10("train", synthetic_n=256)
    reader = batched(map_readers(lambda s: {"x": s[0], "label": s[1]}, r), 64)
    tr.init(jax.random.PRNGKey(0), next(iter(reader())))
    from paddle_tpu.train import events as ev
    accs = []
    tr.train(reader, num_passes=8,
             event_handler=lambda e: accs.append(e.metrics["accuracy"])
             if isinstance(e, ev.EndPass) else None)
    assert accs[-1] > 0.8, accs
