"""TransformerLM showcase: learns a synthetic LM task; flash and MoE
variants agree with / train like the dense-XLA baseline; exports via the IR."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import optim
from paddle_tpu.models import TransformerLM
from paddle_tpu.nn import costs


def _lm_batches(vocab=64, B=16, T=32, n_batches=30, seed=0):
    """First-order Markov stream: each token has 3 likely successors."""
    g = np.random.RandomState(42)
    succ = g.randint(0, vocab, size=(vocab, 3))
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        ids = np.zeros((B, T + 1), np.int32)
        ids[:, 0] = rng.randint(0, vocab, B)
        for t in range(T):
            nxt = succ[ids[:, t], rng.randint(0, 3, B)]
            rand = rng.randint(0, vocab, B)
            ids[:, t + 1] = np.where(rng.rand(B) < 0.9, nxt, rand)
        out.append(ids)
    return out


def _train(model, batches, steps=60, lr=3e-3):
    ids0 = jnp.asarray(batches[0][:, :-1])
    variables = model.init(jax.random.PRNGKey(0), ids0)
    opt = optim.adam(lr)
    opt_state = opt.init(variables["params"])

    @jax.jit
    def step(p, opt_state, sno, inp, tgt):
        def loss_fn(p):
            logits, aux = model.apply({"params": p}, inp, return_aux=True)
            ce = costs.softmax_cross_entropy(
                logits.reshape(-1, logits.shape[-1]), tgt.reshape(-1))
            return jnp.mean(ce) + 0.01 * aux
        loss, g = jax.value_and_grad(loss_fn)(p)
        p, opt_state = opt.apply(g, opt_state, p, sno)
        return loss, p, opt_state

    p = variables["params"]
    first = last = None
    for i in range(steps):
        b = batches[i % len(batches)]
        inp, tgt = jnp.asarray(b[:, :-1]), jnp.asarray(b[:, 1:])
        loss, p, opt_state = step(p, opt_state, jnp.asarray(i), inp, tgt)
        if first is None:
            first = float(loss)
        last = float(loss)
    return first, last, p


def test_transformer_lm_learns():
    model = TransformerLM(vocab=64, dim=64, num_layers=2, num_heads=4,
                          ffn_hidden=128, max_len=64)
    first, last, _ = _train(model, _lm_batches())
    # Markov structure: a learning LM must get well below the ~log(64)=4.16
    # uniform floor and clearly below its starting loss
    assert last < 0.6 * first, (first, last)
    assert last < 3.0


def test_transformer_flash_path_matches_dense():
    batches = _lm_batches(T=64)
    dense = TransformerLM(vocab=64, dim=64, num_layers=1, num_heads=2,
                          ffn_hidden=64, max_len=64, use_flash=False)
    flash = TransformerLM(vocab=64, dim=64, num_layers=1, num_heads=2,
                          ffn_hidden=64, max_len=64, use_flash=True)
    ids = jnp.asarray(batches[0][:, :-1])
    variables = dense.init(jax.random.PRNGKey(0), ids)
    y1 = dense.apply(variables, ids)
    y2 = flash.apply(variables, ids)      # same params, pallas kernel
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=2e-3, atol=2e-3)


def test_transformer_moe_variant_trains():
    model = TransformerLM(vocab=64, dim=64, num_layers=2, num_heads=4,
                          ffn_hidden=64, max_len=64, moe_experts=4)
    first, last, _ = _train(model, _lm_batches(), steps=60)
    assert last < 0.7 * first, (first, last)


def test_transformer_ir_roundtrip():
    from paddle_tpu.core.config import (build_module, config_from_json,
                                        config_to_json, module_config)
    m = TransformerLM(vocab=32, dim=32, num_layers=1, num_heads=2,
                      ffn_hidden=32, max_len=16)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 16)))
    v = m.init(jax.random.PRNGKey(0), ids)
    m2 = build_module(config_from_json(config_to_json(module_config(m))))
    np.testing.assert_allclose(np.asarray(m.apply(v, ids)),
                               np.asarray(m2.apply(v, ids)), rtol=1e-5)


def test_pipeline_parallel_lm_matches_sequential(nprng):
    """TransformerLM through the GPipe block pipeline == plain apply —
    logits AND grads (pipeline parallelism reachable from the model
    library, differentiable end to end incl. embeddings and tied head)."""
    import paddle_tpu as pt
    from paddle_tpu.models.transformer import make_pipeline_lm_apply
    from paddle_tpu.nn import costs

    vocab, T, B, L = 40, 8, 4, 4
    model = TransformerLM(vocab=vocab, dim=16, num_layers=L, num_heads=2,
                          ffn_hidden=32, max_len=T)
    ids = jnp.asarray(nprng.randint(0, vocab, (B, T)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    mesh = pt.make_mesh({"data": 2, "pipe": L})
    pp_apply = make_pipeline_lm_apply(model, mesh, microbatches=2)

    ref = model.apply(variables, ids)
    got = jax.jit(lambda v: pp_apply(v, ids))(variables)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_pp(v):
        logits = pp_apply(v, ids)
        return jnp.mean(costs.softmax_cross_entropy(
            logits.reshape(-1, vocab), ids.reshape(-1)))

    def loss_seq(v):
        logits = model.apply(v, ids)
        return jnp.mean(costs.softmax_cross_entropy(
            logits.reshape(-1, vocab), ids.reshape(-1)))

    gp = jax.jit(jax.grad(loss_pp))(variables)
    gs = jax.grad(loss_seq)(variables)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(gp)[0],
            jax.tree_util.tree_flatten_with_path(gs)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=str(pa))
