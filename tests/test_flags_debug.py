"""Flag/config system + numeric hardening + param-stats telemetry
(the analogs of ``utils/Flags.cpp``, ``TrainerMain.cpp:36`` FP traps, and
``--show_parameter_stats_period``)."""

import dataclasses
import json
import logging

import numpy as np
import jax
import pytest

from paddle_tpu.utils.flags import (TrainerFlags, flags_from_json,
                                    flags_to_json, parse_flags)


def test_flags_defaults_and_cli():
    f = parse_flags(TrainerFlags, [])
    assert f.batch_size == 128 and f.resume is False
    f = parse_flags(TrainerFlags, ["--batch_size", "64", "--resume", "true",
                                   "--learning_rate", "0.5"])
    assert f.batch_size == 64 and f.resume is True
    assert abs(f.learning_rate - 0.5) < 1e-9


def test_flags_env_and_json_precedence(tmp_path, monkeypatch):
    cfg = tmp_path / "flags.json"
    cfg.write_text(json.dumps({"batch_size": 32, "num_passes": 7,
                               "seed": 3}))
    monkeypatch.setenv("PADDLE_TPU_BATCH_SIZE", "48")
    f = parse_flags(TrainerFlags, ["--flags_json", str(cfg),
                                   "--seed", "9"])
    assert f.num_passes == 7          # from json
    assert f.batch_size == 48         # env beats json
    assert f.seed == 9                # cli beats everything


def test_flags_subclass_and_roundtrip():
    @dataclasses.dataclass
    class MyFlags(TrainerFlags):
        extra: float = 2.5

    f = parse_flags(MyFlags, ["--extra", "1.25"])
    assert f.extra == 1.25
    g = flags_from_json(MyFlags, flags_to_json(f))
    assert g == f


def test_assert_finite_names_bad_leaves():
    from paddle_tpu.utils.debug import assert_finite, nonfinite_leaves
    good = {"a": np.ones(3), "b": {"c": np.zeros(2)}}
    assert_finite(good)
    bad = {"a": np.ones(3), "b": {"c": np.array([1.0, np.nan])}}
    leaves = nonfinite_leaves(bad)
    assert len(leaves) == 1 and "c" in leaves[0]
    with pytest.raises(FloatingPointError, match="c"):
        assert_finite(bad, "params")


def test_trainer_nan_check_trips():
    import jax.numpy as jnp
    from paddle_tpu import optim
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.train import Trainer

    # a loss that goes NaN on the second step
    def poisoned_loss(out, b):
        return jnp.log(-jnp.abs(out).sum(-1))      # log of negative -> nan

    tr = Trainer(MnistMLP(), poisoned_loss, optim.sgd(0.1), nan_check=True)
    batch = {"x": np.ones((8, 28, 28, 1), np.float32),
             "label": np.zeros(8, np.int32)}
    tr.init(jax.random.PRNGKey(0), batch)
    with pytest.raises(FloatingPointError, match="non-finite loss"):
        tr.train(lambda: iter([batch]), num_passes=1, log_period=0)


def test_param_stats_telemetry(caplog):
    from paddle_tpu import optim
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    tr = Trainer(MnistMLP(),
                 lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
                 optim.sgd(0.01), param_stats_period=1)
    batch = {"x": np.ones((8, 28, 28, 1), np.float32),
             "label": np.zeros(8, np.int32)}
    tr.init(jax.random.PRNGKey(0), batch)
    with caplog.at_level(logging.INFO, logger="paddle_tpu.trainer"):
        tr.train(lambda: iter([batch]), num_passes=1, log_period=0)
    stats_lines = [r for r in caplog.records if "abs_max" in r.getMessage()]
    assert stats_lines, "no param-stats telemetry emitted"


def test_parse_flags_reads_sys_argv_by_default(monkeypatch):
    monkeypatch.setattr("sys.argv", ["prog", "--batch_size", "99"])
    f = parse_flags(TrainerFlags)
    assert f.batch_size == 99


def test_flags_json_values_are_coerced(tmp_path):
    import json as _json
    cfg = tmp_path / "f.json"
    cfg.write_text(_json.dumps({"learning_rate": "0.25", "resume": "false"}))
    f = parse_flags(TrainerFlags, ["--flags_json", str(cfg)])
    assert isinstance(f.learning_rate, float) and f.learning_rate == 0.25
    assert f.resume is False


def test_flags_optional_none_roundtrip():
    import dataclasses
    import typing

    @dataclasses.dataclass
    class F(TrainerFlags):
        maybe: typing.Optional[str] = None

    f = F()
    g = flags_from_json(F, flags_to_json(f))
    assert g.maybe is None


def test_flags_null_for_required_field_fails_fast(tmp_path):
    import json as _json
    import pytest
    cfg = tmp_path / "f.json"
    cfg.write_text(_json.dumps({"batch_size": None}))
    with pytest.raises(ValueError, match="non-Optional"):
        parse_flags(TrainerFlags, ["--flags_json", str(cfg)])


def test_cli_trains_from_config_alone(tmp_path):
    """The paddle_trainer-style workflow: model IR json + flags, no user
    code (reference: trainer/TrainerMain.cpp)."""
    from paddle_tpu.inference import dump_config
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.train.cli import TrainCliFlags, run

    cfg = tmp_path / "model.json"
    cfg.write_text(dump_config(MnistMLP()))
    flags = parse_flags(TrainCliFlags, [
        "--model_config", str(cfg), "--dataset", "mnist",
        "--num_passes", "2", "--batch_size", "64",
        "--learning_rate", "0.001", "--log_period", "0",
        "--checkpoint_dir", str(tmp_path / "ckpt")])
    metrics = run(flags)
    # synthetic mnist carries 10% label noise (Bayes ceiling ~0.90)
    assert metrics["accuracy"] > 0.8
    import os
    assert any(d.startswith("pass-") for d in os.listdir(tmp_path / "ckpt"))


def test_barrier_stat_single_process():
    from paddle_tpu.utils.stats import BarrierStat
    bs = BarrierStat("step")
    assert bs.gather() == {}          # no sample yet
    bs.update(0.25)
    out = bs.gather()
    assert out["step_min_s"] == out["step_max_s"] == 0.25
    assert out["step_spread"] == 0.0
    assert bs.summary()["samples"] == 1
