"""Fleet observability tests (ISSUE 17): distributed request tracing
merged across replica lanes, the streaming SLO monitor (P² percentiles,
error-budget burn rate), serving anomaly forensics, and the satellites
— child JSONL telemetry sinks, proc-spec schema stability, the report's
serving transport/SLO blocks, and the ``obs.top`` dashboard.

All fleet drills here are in-process on a :class:`SimClock` (the
process-mode twin runs in ``bench.py --fleet-child`` leg 4), so the
determinism assertions are exact: the same drill must produce the same
merged trace, byte for byte."""

import collections
import json
import os
import tempfile

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models import TransformerLM
from paddle_tpu.obs import (InMemorySink, P2Quantile, SLOMonitor,
                            SLOTargets, ServingAnomalyDetector,
                            Telemetry, flow_connected, flow_summary,
                            lane_monotonic, merge_fleet_trace)
from paddle_tpu.obs import report as report_lib
from paddle_tpu.obs import top as top_lib
from paddle_tpu.parallel import multihost
from paddle_tpu.serve import ServingFleet, SimClock
from paddle_tpu.serve.fleet import build_proc_spec
from paddle_tpu.serve.loadgen import make_workload
from paddle_tpu.serve.replica_proc import EventBuffer
from paddle_tpu.train import FaultSchedule

V, W = 64, 24
DT, HB = 0.1, 0.25


@pytest.fixture(scope="module")
def model_and_vars():
    model = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                          ffn_hidden=64, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))
    return model, vs


def _fleet(model, vs, n, *, telemetry=None, faults=None, clock=None,
           heartbeat_timeout_s=HB, **kw):
    return ServingFleet.from_model(
        model, vs, n, engine_kwargs=dict(max_slots=2, block_size=4),
        telemetry=telemetry, faults=faults,
        clock=clock if clock is not None else SimClock(),
        heartbeat_timeout_s=heartbeat_timeout_s, est_tick_s=DT,
        root=tempfile.mkdtemp(prefix="paddle_tpu_fleet_obs_"), **kw)


def _workload(n=6, seed=7):
    return make_workload(n, V, seed=seed, rate_rps=30.0,
                         prompt_len=(2, 6), max_new=(3, 8), max_total=W)


# ---------------------------------------------------------------------------
# P² streaming quantiles
# ---------------------------------------------------------------------------

def test_p2_quantile_tracks_numpy():
    rng = np.random.RandomState(0)
    xs = rng.lognormal(mean=3.0, sigma=0.7, size=5000)
    for p in (50, 95, 99):
        est = P2Quantile(p)
        for x in xs:
            est.observe(x)
        exact = float(np.percentile(xs, p))
        assert est.value() == pytest.approx(exact, rel=0.05), (p, exact)


def test_p2_quantile_exact_below_five_samples():
    est = P2Quantile(50)
    assert est.value() is None
    for x in (3.0, 1.0, 2.0):
        est.observe(x)
    assert est.value() == 2.0                  # nearest-rank, not a model


# ---------------------------------------------------------------------------
# streaming SLO monitor
# ---------------------------------------------------------------------------

def _rec(reason="length", wall=100.0, ttft=10.0, tokens=4, **kw):
    return {"kind": "request", "finish_reason": reason, "wall_ms": wall,
            "ttft_ms": ttft, "tpot_ms": 5.0, "new_tokens": tokens,
            "deadline_s": kw.pop("deadline_s", None), **kw}


def test_slo_burn_rate_is_windowed_bad_over_budget():
    mon = SLOMonitor(targets=SLOTargets(goodput_pct=90.0), window=10)
    for _ in range(5):
        mon.observe(_rec())
    for _ in range(5):
        mon.observe(_rec(reason="timeout"))
    # 50% bad in-window / 10% budget = 5x burn
    assert mon.burn_rate() == pytest.approx(5.0)
    rep = mon.report()
    assert rep["burn_rate"] == pytest.approx(5.0)
    assert rep["goodput_pct"] == pytest.approx(50.0)
    assert rep["window_goodput_pct"] == pytest.approx(50.0)


def test_slo_retried_lineage_and_shed_semantics():
    mon = SLOMonitor(window=8)
    mon.observe(_rec(reason="retried"))
    mon.observe({"kind": "decode_tick"})       # non-request: ignored
    mon.observe(_rec(reason="shed", wall=0.0, ttft=None))
    mon.observe(_rec(wall=200.0))
    rep = mon.report()
    assert rep["requests"] == 2                # shed + good, not retried
    assert rep["retried_attempts"] == 1
    # the shed's wall_ms=0 must NOT drag the latency estimators down
    assert rep["wall_ms_p50"] == pytest.approx(200.0)
    assert mon.burn_rate() > 0.0               # shed burns budget


def test_slo_deadline_and_absolute_targets():
    mon = SLOMonitor(targets=SLOTargets(goodput_pct=50.0, ttft_ms=50.0))
    mon.observe(_rec(ttft=10.0))                          # good
    mon.observe(_rec(ttft=80.0))                          # ttft target blown
    mon.observe(_rec(wall=3000.0, deadline_s=1.0))        # deadline blown
    assert mon.good == 1
    assert mon.report()["goodput_pct"] == pytest.approx(33.33, abs=0.01)


# ---------------------------------------------------------------------------
# distributed tracing: the merged fleet trace
# ---------------------------------------------------------------------------

def _traced_drill(model, vs, *, anomaly=None):
    mem = InMemorySink()
    clock = SimClock()
    faults = FaultSchedule(kill_replica_at_tick=(4, 0))
    fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]),
                   faults=faults, clock=clock, trace=True, slo=True,
                   anomaly=anomaly)
    frs = fleet.play(_workload(), dt_s=DT)
    return fleet, frs, mem


def test_fleet_trace_kill_resubmit_is_one_connected_flow(model_and_vars):
    model, vs = model_and_vars
    fleet, frs, _ = _traced_drill(model, vs)
    tr = fleet.fleet_trace()
    lanes = sorted({e.get("pid") for e in tr["traceEvents"]
                    if e.get("ph") != "M"})
    assert 0 in lanes and len([p for p in lanes if p > 0]) >= 2
    retried = [fr.rid for fr in frs if fr.retries > 0]
    assert retried, "the kill fault must force at least one resubmit"
    for rid in retried:
        assert flow_connected(tr, rid), flow_summary(tr).get(rid)
        # the resubmitted rid's flow touches more than one lane
        pids = {pid for _, pid in flow_summary(tr)[rid]}
        assert len(pids) >= 2, pids
    # EVERY rid's flow is well-formed, not just the resubmitted ones
    for fr in frs:
        assert flow_connected(tr, fr.rid), fr.rid
    assert lane_monotonic(tr)
    names = {e["name"] for e in tr["traceEvents"] if e.get("ph") == "X"}
    assert {"submit", "queue_wait", "decode_tick", "engine_tick",
            "finish", "resubmit", "terminal"} <= names, names
    # Chrome-parseable: a JSON round trip preserves the container
    rt = json.loads(json.dumps(tr))
    assert rt["traceEvents"] and rt["displayTimeUnit"] == "ms"


def test_fleet_trace_merge_is_deterministic(model_and_vars):
    model, vs = model_and_vars
    fleet_a, _, _ = _traced_drill(model, vs)
    fleet_b, _, _ = _traced_drill(model, vs)
    a, b = fleet_a.fleet_trace(), fleet_b.fleet_trace()
    assert json.dumps(a["traceEvents"]) == json.dumps(b["traceEvents"])


def test_fleet_trace_tail_window(model_and_vars):
    model, vs = model_and_vars
    fleet, _, _ = _traced_drill(model, vs)
    full = fleet.fleet_trace()
    tail = fleet.fleet_trace(tail=10)
    n_meta = sum(1 for e in tail["traceEvents"] if e.get("ph") == "M")
    assert len(tail["traceEvents"]) == n_meta + 10
    assert len(full["traceEvents"]) > len(tail["traceEvents"])


def test_observability_off_is_invisible(model_and_vars):
    """Default-off contract: no tracer anywhere, no new stats keys, no
    new telemetry kinds — and the work itself is identical to an
    instrumented run's."""
    model, vs = model_and_vars

    def run(instrumented):
        mem = InMemorySink()
        fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]),
                       faults=FaultSchedule(kill_replica_at_tick=(4, 0)),
                       clock=SimClock(), trace=instrumented,
                       slo=instrumented)
        frs = fleet.play(_workload(), dt_s=DT)
        return fleet, frs, mem

    dark, frs_dark, mem_dark = run(False)
    lit, frs_lit, _ = run(True)
    assert dark.tracer is None and dark.slo is None
    assert all(w.tracer is None for w in dark.workers)
    assert dark.fleet_trace() is None and dark.slo_report() is None
    st = dark.stats()
    assert "slo" not in st and "anomalies" not in st
    with pytest.raises(ValueError):
        dark.save_fleet_trace("/tmp/nope.json")
    # the pre-PR telemetry vocabulary, exactly — instrumentation adds
    # no record kinds when off
    kinds = {r.get("kind") for r in mem_dark.records}
    assert "fleet" not in kinds
    # zero observer effect: identical tokens + reasons per rid
    assert ({fr.rid: (fr.finish_reason, list(fr.tokens))
             for fr in frs_dark}
            == {fr.rid: (fr.finish_reason, list(fr.tokens))
                for fr in frs_lit})


def test_slo_rides_fleet_stats_and_fleet_record(model_and_vars):
    model, vs = model_and_vars
    fleet, frs, mem = _traced_drill(model, vs)
    st = fleet.stats()
    assert "burn_rate" in st["slo"]
    assert st["slo"]["requests"] == len(frs)
    assert st["transport"] == {"errors": 0, "retransmits": 0,
                               "timeouts": 0, "corrupt_replies": 0}
    rec = fleet.emit_stats()
    assert rec["kind"] == "fleet" and "slo" in rec and "transport" in rec
    assert any(r.get("kind") == "fleet" for r in mem.records)


# ---------------------------------------------------------------------------
# serving anomaly forensics
# ---------------------------------------------------------------------------

def test_tick_stall_fires_with_forensic_bundle(model_and_vars):
    model, vs = model_and_vars
    out = tempfile.mkdtemp(prefix="paddle_tpu_anom_")
    anom = ServingAnomalyDetector(out_dir=out, stall_ticks=2)
    mem = InMemorySink()
    clock = SimClock()
    faults = FaultSchedule(stall_replica_at_tick=(3, 1, 4))
    # long heartbeat so the stall stays a stall, not a death verdict
    fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]),
                   faults=faults, clock=clock, trace=True, slo=True,
                   anomaly=anom, heartbeat_timeout_s=10.0)
    fleet.play(_workload(8), dt_s=DT)
    kinds = [v.kind for v in anom.verdicts]
    assert "tick_stall" in kinds, kinds
    bundle = next(b for b in anom.bundles if "tick_stall_r1" in b)
    files = set(os.listdir(bundle))
    assert {"verdict.json", "tick_ring.jsonl", "records_tail.jsonl",
            "heartbeats.json", "transport.json",
            "fleet_trace_tail.json"} <= files, files
    v = json.load(open(os.path.join(bundle, "verdict.json")))
    assert v["replica"] == 1
    assert v["verdict"]["kind"] == "tick_stall"
    # the bound trace tail is a real merged trace container
    tt = json.load(open(os.path.join(bundle, "fleet_trace_tail.json")))
    assert "traceEvents" in tt
    # one-shot: the same kind cannot fire twice for the same replica
    assert kinds.count("tick_stall") == 1


def test_serving_anomaly_kinds_unit():
    out = tempfile.mkdtemp(prefix="paddle_tpu_anom_unit_")
    det = ServingAnomalyDetector(out_dir=out, stall_ticks=3,
                                 accept_floor=0.2, accept_window=3,
                                 prefix_window=3, retransmit_burst=3,
                                 queue_growth=4, queue_window=4)
    # accept_collapse: healthy then floor-pinned for a full window
    base = {"kind": "request", "finish_reason": "length"}
    det.observe_serving(0, dict(base, draft_proposed=10,
                                draft_accepted=8))
    fired = []
    for _ in range(3):
        fired += det.observe_serving(0, dict(base, draft_proposed=10,
                                             draft_accepted=1))
    assert [v.kind for v in fired] == ["accept_collapse"]
    # prefix_hit_collapse: hits before, none across the window
    det.observe_serving(1, dict(base, prefix_hit_blocks=4))
    fired = []
    for _ in range(3):
        fired += det.observe_serving(1, dict(base, prefix_hit_blocks=0))
    assert [v.kind for v in fired] == ["prefix_hit_collapse"]
    # retransmit_burst: cumulative counter rises >= threshold in-window
    assert det.observe_transport(2, {"retransmits": 0}) == []
    fired = det.observe_transport(2, {"retransmits": 4})
    assert [v.kind for v in fired] == ["retransmit_burst"]
    # queue_divergence: monotone growth across a full window
    fired = []
    for tick, q in enumerate((0, 2, 4, 6)):
        fired += det.observe_fleet_tick(3, tick=tick, engine_ticks=tick,
                                        queued=q, busy=True)
    assert [v.kind for v in fired] == ["queue_divergence"]
    # per-replica one-shot isolation: replica 4 can still fire the kind
    # replica 3 used up
    fired = []
    for tick, q in enumerate((0, 2, 4, 6)):
        fired += det.observe_fleet_tick(4, tick=tick, engine_ticks=tick,
                                        queued=q, busy=True)
    assert [v.kind for v in fired] == ["queue_divergence"]
    # retried lineage records never feed detection
    assert det.observe_serving(0, dict(base, finish_reason="retried",
                                       draft_proposed=10,
                                       draft_accepted=0)) == []
    assert len(det.bundles) == 5


# ---------------------------------------------------------------------------
# satellites: child JSONL sink, spec stability, report, top
# ---------------------------------------------------------------------------

def test_event_buffer_jsonl_sink(tmp_path):
    path = str(tmp_path / "deep" / "replica_0.jsonl")
    buf = EventBuffer(jsonl_path=path)
    buf.emit_event({"kind": "request", "rid": 1})
    buf.emit_event({"kind": "decode_tick", "tick": 0})
    # the file is line-flushed per record: readable NOW, mid-"run",
    # exactly what a post-SIGKILL post-mortem needs
    rows = [json.loads(l) for l in open(path)]
    assert [r["kind"] for r in rows] == ["request", "decode_tick"]
    assert len(buf.drain()) == 2              # shipping unchanged
    assert EventBuffer().drain() == []        # sink-less default


def test_build_proc_spec_schema_stability(model_and_vars):
    model, vs = model_and_vars
    root = tempfile.mkdtemp(prefix="paddle_tpu_spec_")
    old = build_proc_spec(model, vs, root, engine_kwargs={})
    assert "telemetry_dir" not in old and "trace" not in old
    unset = build_proc_spec(model, vs, root, engine_kwargs={},
                            telemetry_dir=None)
    assert unset == old                       # absent-when-unset
    td = os.path.join(root, "tel")
    new = build_proc_spec(model, vs, root, engine_kwargs={},
                          telemetry_dir=td)
    assert new.pop("telemetry_dir") == td
    assert new == old                         # ONLY the new key differs


def test_report_surfaces_transport_and_slo(model_and_vars):
    model, vs = model_and_vars
    fleet, _, mem = _traced_drill(model, vs)
    fleet.emit_stats()
    s = report_lib.summarize(mem.records)
    assert s["serving"]["transport"]["retransmits"] == 0
    assert "burn_rate" in s["serving"]["slo"]
    text = report_lib.format_summary(s)
    assert "transport" in text and "slo (streaming)" in text
    assert "burn rate" in text
    # fallback: no fleet record, classified transport EVENTS only
    evs = [{"kind": "transport", "event": "timeout", "replica": 0,
            "op": "tick"},
           {"kind": "transport", "event": "corrupt", "replica": 0,
            "op": "tick"}]
    s2 = report_lib.summarize(evs)
    assert s2["serving"]["transport"]["events"] == 2
    assert s2["serving"]["transport"]["timeout"] == 1


def test_top_render_and_once(tmp_path):
    root = str(tmp_path / "fleet")
    multihost.write_heartbeat(root, host_id=0, seq=3, now=100.0,
                              extra={"queued": 2, "running": 1,
                                     "free_blocks": 7})
    jsonl = str(tmp_path / "tel.jsonl")
    with open(jsonl, "w") as f:
        f.write(json.dumps(_rec()) + "\n")
        f.write(json.dumps(_rec(reason="timeout")) + "\n")
    frame = top_lib.render(root, jsonl, now=100.5)
    assert "replica" in frame and "0" in frame
    assert "burn_rate" in frame and "ttft_ms" in frame
    assert "length=1" in frame and "timeout=1" in frame
    assert top_lib.main(["--root", root, "--jsonl", jsonl,
                         "--once"]) == 0


def test_merge_fleet_trace_canonicalizes_pids_and_tids():
    router = [{"ph": "M", "name": "process_name", "pid": 999, "tid": 0,
               "args": {"name": "x"}},
              {"ph": "X", "name": "submit", "pid": 999, "tid": 1234,
               "ts": 1.0, "dur": 1.0}]
    replica = {0: [{"ph": "X", "name": "decode_tick", "pid": 31337,
                    "tid": 777, "ts": 2.0, "dur": 1.0}]}
    tr = merge_fleet_trace(router, replica)
    evs = [e for e in tr["traceEvents"] if e.get("ph") != "M"]
    assert [e["pid"] for e in evs] == [0, 1]   # router=0, replica r=r+1
    assert all(e["tid"] == 1 for e in evs)     # first-appearance order
    metas = [e for e in tr["traceEvents"] if e.get("ph") == "M"]
    names = {m["args"]["name"] for m in metas}
    assert {"fleet-router", "replica 0"} <= names
