"""1x1-conv matmul/Pallas path (nn/pallas_conv.py): numeric oracles.

The bottleneck-backward perf lever (PERF.md r3 -> r4): forward, dx and the
Pallas-accumulated dW must match the lax.conv path exactly; Conv2D must
produce identical models under every ``set_conv1x1_impl`` choice."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu import nn
from paddle_tpu.nn import pallas_conv
from paddle_tpu.nn.layers import set_conv1x1_impl


@pytest.fixture
def nprng():
    return np.random.RandomState(0)


def conv_form(x, w):
    return lax.conv_general_dilated(
        x, w.reshape(1, 1, *w.shape), window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def test_dw_pallas_matches_oracle(nprng):
    m, cin, cout = 160, 8, 24
    x = jnp.asarray(nprng.normal(size=(m, cin)).astype(np.float32))
    dy = jnp.asarray(nprng.normal(size=(m, cout)).astype(np.float32))
    got = pallas_conv.dw_pallas(x, dy)
    np.testing.assert_allclose(np.asarray(got), np.asarray(x.T @ dy),
                               rtol=1e-5, atol=1e-5)


def test_dw_pallas_single_chunk_odd_m(nprng):
    # m prime-ish: falls back to one chunk
    m, cin, cout = 34, 8, 8
    x = jnp.asarray(nprng.normal(size=(m, cin)).astype(np.float32))
    dy = jnp.asarray(nprng.normal(size=(m, cout)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(pallas_conv.dw_pallas(x, dy)),
                               np.asarray(x.T @ dy), rtol=1e-5, atol=1e-5)


def test_conv1x1_value_and_grads_match_conv(nprng):
    b, h, w_, cin, cout = 4, 6, 6, 8, 16
    x = jnp.asarray(nprng.normal(size=(b, h, w_, cin)).astype(np.float32))
    w = jnp.asarray(nprng.normal(size=(cin, cout)).astype(np.float32) * 0.1)
    dy = jnp.asarray(nprng.normal(size=(b, h, w_, cout)).astype(np.float32))

    y1, vjp1 = jax.vjp(pallas_conv.conv1x1, x, w)
    y2, vjp2 = jax.vjp(conv_form, x, w)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-5, atol=1e-5)
    (dx1, dw1), (dx2, dw2) = vjp1(dy), vjp2(dy)
    np.testing.assert_allclose(np.asarray(dx1), np.asarray(dx2),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw1), np.asarray(dw2),
                               rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("impl", ["matmul", "pallas"])
@pytest.mark.parametrize("stride", [1, 2])
def test_conv2d_impls_agree(nprng, impl, stride):
    """Conv2D(1x1) under matmul/pallas == the conv lowering, values AND
    parameter grads, including the strided (shortcut-downsample) case."""
    x = jnp.asarray(nprng.normal(size=(2, 8, 8, 6)).astype(np.float32))
    m = nn.Conv2D(10, 1, stride=stride, padding="SAME", name="c")
    variables = m.init(jax.random.PRNGKey(0), x)

    def loss(params):
        return jnp.sum(m.apply({"params": params}, x) ** 2)

    prev = set_conv1x1_impl("conv")
    try:
        want_y = m.apply(variables, x)
        want_g = jax.grad(loss)(variables["params"])
        set_conv1x1_impl(impl)
        got_y = m.apply(variables, x)
        got_g = jax.grad(loss)(variables["params"])
    finally:
        set_conv1x1_impl(prev)
    np.testing.assert_allclose(np.asarray(got_y), np.asarray(want_y),
                               rtol=1e-5, atol=1e-5)
    for (pa, a), (_, b_) in zip(
            jax.tree_util.tree_flatten_with_path(got_g)[0],
            jax.tree_util.tree_flatten_with_path(want_g)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-4, err_msg=str(pa))


def test_conv2d_3x3_unaffected_by_impl(nprng):
    """Non-1x1 convs must ignore the impl switch."""
    x = jnp.asarray(nprng.normal(size=(2, 8, 8, 4)).astype(np.float32))
    m = nn.Conv2D(8, 3, padding="SAME", name="c")
    variables = m.init(jax.random.PRNGKey(0), x)
    prev = set_conv1x1_impl("pallas")
    try:
        got = m.apply(variables, x)
    finally:
        set_conv1x1_impl(prev)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(m.apply(variables, x)),
                               rtol=1e-6, atol=1e-6)
