"""Bucketed gradient-sync overlap equivalence suite (ISSUE 8 tentpole).

The contract under test: ``Trainer(grad_sync="bucketed")`` — explicit
per-bucket dp grad all-reduces anchored inside the backward — reproduces
``grad_sync="fused"`` (one flat post-backward all-reduce) bit-for-bit in
f32 on a 2-device dp mesh: params and per-step losses, composing with
``grad_accum > 1``, ``steps_per_call > 1``, ``param_sharding``, the
remat'd scan-over-layers stack (per-layer in-scan sync), and the
pipelined host loop. Plus: the HLO gate (bucketed >= 2 gradient
all-reduces where fused yields exactly 1), the bucket partitioner's
invariants, and the graceful no-dp fallback.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import optim, parallel
from paddle_tpu.core.module import Module
from paddle_tpu.nn import costs
from paddle_tpu.parallel import overlap
from paddle_tpu.train import Trainer, events as ev


class MLP(Module):
    def __init__(self, hidden=32, classes=8):
        super().__init__()
        self.hidden = nn.Linear(hidden, act="relu", name="hidden")
        self.out = nn.Linear(classes, name="out")

    def forward(self, x, train=False):
        return self.out(self.hidden(x))


MLP_RULES = parallel.ShardingRules([
    ("*/hidden/w", P(None, "model")),
    ("*/hidden/b", P("model")),
    ("*/out/w", P("model", None)),
])


def _batches(n=8, bs=32, d=16, classes=8, seed=0, weighted=False):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        b = {"x": rng.normal(size=(bs, d)).astype(np.float32),
             "label": rng.randint(0, classes, bs).astype(np.int32)}
        if weighted:
            b["weight"] = rng.randint(0, 3, bs).astype(np.float32)
        out.append(b)
    return out


def _dp_mesh(n=2):
    return pt.make_mesh({"data": n}, devices=jax.devices()[:n])


def _make_trainer(batches, grad_sync, K=2, M=1, bucket_mb=0.0005,
                  mesh=None, param_sharding=None, pipeline_depth=1):
    tr = Trainer(
        model=MLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3),
        mesh=mesh if mesh is not None else _dp_mesh(),
        param_sharding=param_sharding, steps_per_call=K, grad_accum=M,
        grad_sync=grad_sync, bucket_mb=bucket_mb,
        pipeline_depth=pipeline_depth)
    tr.init(jax.random.PRNGKey(0), batches[0])
    return tr


def _run(tr, batches, num_passes=1):
    losses = []

    def handler(e):
        if isinstance(e, ev.EndIteration):
            losses.append(e.cost)

    tr.train(lambda: iter(batches), num_passes=num_passes,
             event_handler=handler, log_period=0)
    return jax.device_get(tr.train_state.params), losses


def _assert_trees_equal(a, b):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def _grad_sync_rows(tr, batches):
    """Per-bucket grad all-reduce rows of the trainer's compiled step."""
    rep = tr.attribution_report(batches, emit=False)
    gar = (rep["comm"] or {}).get("grad_allreduce") or {}
    return gar.get("buckets") or []


# ---------------------------------------------------------------------------
# bucket partition invariants
# ---------------------------------------------------------------------------

def test_partition_buckets_reverse_order_and_budget():
    params = {"a": {"w": jnp.zeros((256, 256)),       # 256 KiB
                    "b": jnp.zeros((256,))},
              "z": {"w": jnp.zeros((256, 256)),
                    "b": jnp.zeros((256,))}}
    buckets = overlap.partition_buckets(params, bucket_mb=0.3)
    # reverse flatten order: z's leaves close first
    assert buckets[0].paths[0].startswith("z/")
    all_paths = [p for b in buckets for p in b.paths]
    assert all_paths == ["z/w", "z/b", "a/w", "a/b"]
    # 0.3 MiB budget cannot hold two 256 KiB weights in one bucket
    assert len(buckets) >= 2
    for b in buckets:
        assert b.bytes > 0 and b.dtype == "float32"
    # a huge budget collapses to a single bucket
    assert len(overlap.partition_buckets(params, bucket_mb=1e9)) == 1


def test_partition_buckets_dtype_split_and_exclude():
    params = {"f32": jnp.zeros((8,), jnp.float32),
              "bf16": jnp.zeros((8,), jnp.bfloat16),
              "ids": jnp.zeros((8,), jnp.int32),          # non-inexact
              "block0": {"w": jnp.zeros((8,))}}
    buckets = overlap.partition_buckets(params, bucket_mb=1e9,
                                        exclude=("*block*",))
    dtypes = {b.dtype for b in buckets}
    assert dtypes == {"float32", "bfloat16"}
    all_paths = [p for b in buckets for p in b.paths]
    assert "ids" not in all_paths                          # no cotangent
    assert not any("block0" in p for p in all_paths)       # excluded
    assert overlap.partition_buckets({}, bucket_mb=1.0) == []
    with pytest.raises(ValueError):
        overlap.partition_buckets(params, bucket_mb=0)


# ---------------------------------------------------------------------------
# bucketed == fused, bit-exact in f32 (2-device dp mesh)
# ---------------------------------------------------------------------------

def test_bucketed_equals_fused_bitexact():
    batches = _batches(8)
    pb, lb = _run(_make_trainer(batches, "bucketed"), batches)
    pf, lf = _run(_make_trainer(batches, "fused"), batches)
    assert lb == lf
    _assert_trees_equal(pb, pf)
    # sanity vs the implicit partitioner sync: same math, different
    # reduction anchoring — allclose, not bit-exact
    pn, ln_ = _run(_make_trainer(batches, None), batches)
    assert np.allclose(lb, ln_, rtol=1e-4, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(pb),
                    jax.tree_util.tree_leaves(pn)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_composes_with_grad_accum_and_weighted_batches():
    """grad_accum > 1: local grads accumulate across microbatches and the
    bucketed/fused sync fires once per optimizer step — bit-exact across
    the two modes, with weighted (zero-weight-included) batches."""
    batches = _batches(8, weighted=True)
    pb, lb = _run(_make_trainer(batches, "bucketed", K=2, M=2), batches)
    pf, lf = _run(_make_trainer(batches, "fused", K=2, M=2), batches)
    assert lb == lf and len(lb) == 4
    _assert_trees_equal(pb, pf)


def test_composes_with_param_sharding():
    """Tensor-parallel param_sharding (model axis) stays GSPMD-auto
    inside the manual-dp region: bucketed and fused agree to last-ulp
    tolerance and the committed layout survives training. (Bit-exactness
    is the PURE-DP contract: under auto tp the partitioner may pick
    different intermediate shardings for the two programs, re-associating
    feature-axis reductions — observed delta ~1e-8.)"""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    mesh = pt.make_mesh({"data": 2, "model": 2},
                        devices=jax.devices()[:4])
    batches = _batches(8)
    tr_b = _make_trainer(batches, "bucketed", mesh=mesh,
                         param_sharding=MLP_RULES)
    tr_f = _make_trainer(batches, "fused", mesh=mesh,
                         param_sharding=MLP_RULES)
    pb, lb = _run(tr_b, batches)
    pf, lf = _run(tr_f, batches)
    np.testing.assert_allclose(lb, lf, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(pb),
                    jax.tree_util.tree_leaves(pf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    root = next(iter(tr_b.train_state.params))
    w = tr_b.train_state.params[root]["hidden"]["w"]
    assert tuple(w.sharding.spec) == (None, "model")


def test_composes_with_pipelined_host_loop():
    """The async host pipeline defers host bookkeeping, not device math:
    a pipelined bucketed run reproduces the serial bucketed run (and the
    fused one) bit-exact."""
    batches = _batches(8)
    ps, ls = _run(_make_trainer(batches, "bucketed"), batches)
    pp, lp = _run(_make_trainer(batches, "bucketed", pipeline_depth=3),
                  batches)
    assert ls == lp
    _assert_trees_equal(ps, pp)


# ---------------------------------------------------------------------------
# HLO gate: all-reduce counts + backward anchoring
# ---------------------------------------------------------------------------

def test_hlo_bucketed_vs_fused_allreduce_counts():
    batches = _batches(4)
    tr_b = _make_trainer(batches, "bucketed")
    tr_f = _make_trainer(batches, "fused")
    rows_b = _grad_sync_rows(tr_b, batches[:2])
    rows_f = _grad_sync_rows(tr_f, batches[:2])
    assert len(rows_b) >= 2, rows_b
    assert len(rows_f) == 1, rows_f
    # every row carries the sched_distance field (None on CPU's
    # synchronous all-reduces; an int for async start/done pairs)
    for r in rows_b + rows_f:
        assert "sched_distance" in r
    # the markers' psums are traced in the backward: transpose metadata
    # must mark the rows backward=True in the full collective table
    rep = tr_b.attribution_report(batches[:2], emit=False)
    gs = [c for c in rep["collectives"]
          if c["scope"].startswith("grad_sync")]
    assert gs and all(c["overlappable"] for c in gs)
    assert any(c["backward"] for c in gs)


def test_hlo_default_mode_has_no_grad_sync_scopes():
    """grad_sync=None is the pre-overlap program: no grad_sync scopes in
    the collective table; the implicit (transpose-metadata) grad
    all-reduces of the scoped transformer are still classified, with an
    empty per-bucket row list."""
    batches = _lm_batches()
    tr = _make_lm_trainer(batches, None)
    rep = tr.attribution_report(batches[:2], emit=False)
    assert not [c for c in rep["collectives"]
                if c["scope"].startswith("grad_sync")]
    gar = (rep["comm"] or {}).get("grad_allreduce")
    assert gar is not None and gar["ops"] >= 1
    assert gar["buckets"] == []


# ---------------------------------------------------------------------------
# the remat'd transformer: per-layer in-scan sync
# ---------------------------------------------------------------------------

def _make_lm_trainer(batches, grad_sync, V=64, T=16, K=2):
    from paddle_tpu.models import TransformerLM
    tr = Trainer(
        model=TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                            ffn_hidden=64, max_len=T, remat="dots"),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(
            out.reshape(-1, V), b["y"].reshape(-1)),
        optimizer=optim.adam(1e-3), mesh=_dp_mesh(), steps_per_call=K,
        grad_sync=grad_sync, bucket_mb=0.0005)
    tr.init(jax.random.PRNGKey(0), batches[0])
    return tr


def _lm_batches(n=4, V=64, T=16, bs=8):
    rng = np.random.RandomState(0)
    return [{"x": rng.randint(0, V, (bs, T)).astype(np.int32),
             "y": rng.randint(0, V, (bs, T)).astype(np.int32)}
            for _ in range(n)]


def test_transformer_in_scan_sync_bitexact_and_in_loop():
    batches = _lm_batches()
    tr_b = _make_lm_trainer(batches, "bucketed")
    tr_f = _make_lm_trainer(batches, "fused")
    pb, lb = _run(tr_b, batches)
    pf, lf = _run(tr_f, batches)
    assert lb == lf
    _assert_trees_equal(pb, pf)
    rows = _grad_sync_rows(tr_b, batches[:2])
    # the per-layer in-scan sync executes K * L times per dispatch — a
    # multiplier above K proves the all-reduce sits INSIDE the backward
    # layer scan, not after it
    scan_rows = [r for r in rows if r["scope"] == "grad_sync/scan_layer"]
    assert scan_rows and scan_rows[0]["multiplier"] > 2
    # embed/pos/head leaves still sync via top-level buckets
    assert [r for r in rows if r["scope"].startswith("grad_sync/bucket")]


def test_transformer_scan_claim_protocol():
    from paddle_tpu.models import TransformerLM
    lm = TransformerLM(vocab=32, dim=16, num_layers=2, num_heads=2,
                       ffn_hidden=32, max_len=8, remat="dots")
    assert lm.grad_sync_scan_paths() == ("*/block*/*",)
    # without remat the stack is a plain loop: nothing to claim, block
    # leaves stay in the top-level buckets
    lm_plain = TransformerLM(vocab=32, dim=16, num_layers=2, num_heads=2,
                             ffn_hidden=32, max_len=8)
    assert lm_plain.grad_sync_scan_paths() == ()
    # the hook is a no-op outside an active sync scope
    tree = {"w": jnp.ones((2, 2))}
    assert overlap.sync_scan_slice(tree) is tree


def test_sync_scan_slice_mixed_dtypes():
    """The in-scan hook groups a mixed-precision layer slice by dtype
    (flat psum buffers cannot mix — concatenate would promote and the
    cotangents would come back wrong-typed) and passes non-inexact
    leaves through unmarked."""
    from jax import lax
    mesh = _dp_mesh()
    tree = {"w": jnp.ones((4, 4), jnp.bfloat16),
            "scale": jnp.ones((4,), jnp.float32),
            "ids": jnp.arange(4, dtype=jnp.int32)}

    def per_device(t):
        ids = t["ids"]

        def local(sub):
            with overlap.scan_sync_scope("data"):
                marked = overlap.sync_scan_slice({**sub, "ids": ids},
                                                 tag="mixed")
            return (jnp.sum(marked["w"].astype(jnp.float32))
                    + jnp.sum(marked["scale"])
                    + jnp.sum(marked["ids"]).astype(jnp.float32) * 0.0)

        sub = {"w": t["w"], "scale": t["scale"]}
        s, g = jax.value_and_grad(local)(sub)
        return lax.psum(s, "data"), g

    gspec = {"w": P(), "scale": P()}
    sm = overlap.shard_map_compat(
        per_device, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), tree),),
        out_specs=(P(), gspec))
    s, g = jax.jit(sm)(tree)
    assert g["w"].dtype == jnp.bfloat16
    assert g["scale"].dtype == jnp.float32
    # both devices contributed: cotangent 1 psum'd over dp=2
    np.testing.assert_array_equal(np.asarray(g["scale"]),
                                  np.full((4,), 2.0, np.float32))


# ---------------------------------------------------------------------------
# graceful fallback
# ---------------------------------------------------------------------------

def test_fallback_single_device_dp_warns_once(caplog):
    batches = _batches(4)
    mesh = pt.make_mesh({"data": 1}, devices=jax.devices()[:1])
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.trainer"):
        tr = _make_trainer(batches, "bucketed", mesh=mesh)
        pb, lb = _run(tr, batches)
        # same mesh, implicit sync: the degraded program IS the default
        tr_n = _make_trainer(batches, None, mesh=mesh)
        pn, ln_ = _run(tr_n, batches)
    assert lb == ln_
    _assert_trees_equal(pb, pn)
    warns = [r for r in caplog.records
             if "cannot engage" in r.getMessage()]
    assert len(warns) == 1                      # one-shot per trainer


def test_fallback_fsdp_style_param_sharding_warns(caplog):
    """param_sharding over the dp axis itself (FSDP-style): the explicit
    sync must decline (shards are not replicas) and degrade."""
    batches = _batches(4)
    rules = parallel.ShardingRules([("*/hidden/w", P(None, "data"))])
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.trainer"):
        tr = _make_trainer(batches, "bucketed", mesh=_dp_mesh(),
                           param_sharding=rules)
        _, lb = _run(tr, batches)
    assert all(np.isfinite(l) for l in lb)
    assert any("cannot engage" in r.getMessage() for r in caplog.records)


def test_invalid_mode_and_bucket_mb_raise():
    with pytest.raises(ValueError):
        Trainer(model=MLP(), loss_fn=lambda o, b: o, optimizer=optim.sgd(0.1),
                grad_sync="nope")
    with pytest.raises(ValueError):
        Trainer(model=MLP(), loss_fn=lambda o, b: o, optimizer=optim.sgd(0.1),
                grad_sync="bucketed", bucket_mb=0.0)


# ---------------------------------------------------------------------------
# xla_flags helper (ISSUE 8 satellite)
# ---------------------------------------------------------------------------

def test_xla_flags_assembly_and_merge():
    from paddle_tpu.obs import xla_flags
    core = xla_flags.overlap_flags()
    assert all(f.startswith("--xla_") and "=" in f for f in core)
    assert len(xla_flags.overlap_flags(strict=True)) > len(core)
    # operator-set values win; order is existing-first
    merged = xla_flags.merge_xla_flags(
        ["--xla_tpu_enable_async_collective_fusion=true", "--b=2"],
        existing="--xla_tpu_enable_async_collective_fusion=false")
    assert merged.split() == [
        "--xla_tpu_enable_async_collective_fusion=false", "--b=2"]
    # no TPU hints, no force: environment untouched
    env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "--a=1"}
    assert xla_flags.apply_overlap_flags(env=env) == "--a=1"
    assert env["XLA_FLAGS"] == "--a=1"
    # forced: merged in, operator flags first and preserved
    out = xla_flags.apply_overlap_flags(env=env, force=True)
    assert out.startswith("--a=1") and env["XLA_FLAGS"] == out
    assert "--xla_tpu_enable_async_collective_fusion=true" in out.split()
