"""Module system tests: init/apply purity, naming, sharing, state, rngs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core import Module, Sequential, initializers as I
from paddle_tpu.core.module import current_rng, ModuleError


class Dense(Module):
    def __init__(self, features, name=None):
        super().__init__(name=name)
        self.features = features

    def forward(self, x):
        w = self.param("w", I.xavier_uniform, (x.shape[-1], self.features))
        b = self.param("b", I.zeros, (self.features,))
        return x @ w + b


class MLP(Module):
    def __init__(self):
        super().__init__()
        self.fc1 = Dense(8)
        self.fc2 = Dense(4)

    def forward(self, x):
        return self.fc2(jax.nn.relu(self.fc1(x)))


def test_init_apply_roundtrip(rng):
    m = MLP()
    x = jnp.ones((2, 16))
    vs = m.init(rng, x)
    assert set(vs["params"].keys()) == {"MLP_0"}
    inner = vs["params"]["MLP_0"]
    assert set(inner.keys()) == {"fc1", "fc2"}
    assert inner["fc1"]["w"].shape == (16, 8)
    y = m.apply(vs, x)
    assert y.shape == (2, 4)
    # pure: same inputs -> same outputs
    np.testing.assert_array_equal(y, m.apply(vs, x))


def test_jit_grad_compose(rng):
    m = MLP()
    x = jnp.ones((2, 16))
    vs = m.init(rng, x)

    @jax.jit
    def loss(params, x):
        return jnp.sum(m.apply({"params": params, "state": {}}, x) ** 2)

    g = jax.grad(loss)(vs["params"], x)
    assert jax.tree_util.tree_structure(g) == jax.tree_util.tree_structure(
        vs["params"])


def test_param_sharing(rng):
    shared = Dense(4, name="shared")

    class Net(Module):
        def __init__(self):
            super().__init__()
            self.d = shared

        def forward(self, x):
            return self.d(x) + self.d(x)

    n = Net()
    x = jnp.ones((1, 4))
    vs = n.init(rng, x)
    flat = jax.tree_util.tree_leaves(vs["params"])
    assert len(flat) == 2  # one w, one b — shared across both calls


def test_autonaming_deterministic(rng):
    class Net(Module):
        def forward(self, x):
            a = Dense(3)
            b = Dense(3)
            return b(a(x))

    n = Net()
    x = jnp.ones((1, 3))
    vs = n.init(rng, x)
    y1 = n.apply(vs, x)
    y2 = Net().apply(vs, x)
    np.testing.assert_allclose(y1, y2)


def test_state_mutation(rng):
    class Counter(Module):
        def forward(self, x):
            c = self.state("count", lambda: jnp.zeros(()))
            self.update_state("count", c + 1)
            return x

    m = Counter()
    vs = m.init(rng, jnp.ones(()))
    assert vs["state"]["Counter_0"]["count"] == 1
    out, new = m.apply(vs, jnp.ones(()), mutable=("state",))
    assert new["state"]["Counter_0"]["count"] == 2
    # without mutable: writes are dropped, vs unchanged
    m.apply(vs, jnp.ones(()))
    assert vs["state"]["Counter_0"]["count"] == 1


def test_rng_streams(rng):
    class Noisy(Module):
        def forward(self, x):
            return x + jax.random.normal(current_rng("noise"), x.shape)

    m = Noisy()
    x = jnp.zeros((4,))
    vs = m.init(rng, x, rngs={"noise": rng})
    a = m.apply(vs, x, rngs={"noise": jax.random.PRNGKey(1)})
    b = m.apply(vs, x, rngs={"noise": jax.random.PRNGKey(2)})
    assert not np.allclose(a, b)
    with pytest.raises(ModuleError):
        m.apply(vs, x)  # missing rng


def test_sequential(rng):
    m = Sequential(Dense(8), Dense(2))
    x = jnp.ones((3, 5))
    vs = m.init(rng, x)
    assert m.apply(vs, x).shape == (3, 2)


def test_missing_param_raises(rng):
    m = Dense(4)
    vs = m.init(rng, jnp.ones((1, 3)))
    with pytest.raises(Exception):
        m.apply(vs, jnp.ones((1, 5)))  # shape mismatch -> matmul error or missing
