"""Sequence machinery tests: RNN masking/packing semantics, CRF and CTC vs
brute-force oracles (the analog of test_CRFLayerGrad / LinearChainCTC tests),
sequence ops vs numpy, attention shapes/masking."""

import itertools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.nn as nn
from paddle_tpu.nn import sequence_ops as sq
from paddle_tpu.nn.crf import crf_log_likelihood, crf_decode
from paddle_tpu.nn.ctc import ctc_loss, ctc_greedy_decode


# ---------------------------------------------------------------- RNN / cells

def test_lstm_shapes_and_mask_freeze(rng):
    cell = nn.LSTMCell(8)
    rnn = nn.RNN(cell)
    x = jax.random.normal(rng, (3, 5, 4))
    lengths = jnp.array([5, 3, 0])
    mask = (jnp.arange(5)[None, :] < lengths[:, None]).astype(jnp.float32)
    vs = rnn.init(rng, x, mask=mask)
    out, (h, c) = rnn.apply(vs, x, mask=mask)
    assert out.shape == (3, 5, 8)
    # padded outputs are zero
    np.testing.assert_array_equal(np.asarray(out[1, 3:]), 0.0)
    # frozen state equals state at last valid step
    out2, (h2, c2) = rnn.apply(vs, x[:, :3], mask=mask[:, :3])
    np.testing.assert_allclose(np.asarray(h[1]), np.asarray(h2[1]), rtol=1e-5)


def test_rnn_reverse_matches_flipped(rng):
    cell = nn.GRUCell(6)
    fwd = nn.RNN(cell)
    x = jax.random.normal(rng, (2, 4, 3))
    vs = fwd.init(rng, x)
    rev = nn.RNN(cell, reverse=True)
    out_r, _ = rev.apply(vs, x)
    out_f, _ = fwd.apply(vs, x[:, ::-1])
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_f[:, ::-1]),
                               rtol=1e-5)


def test_rnn_segment_reset(rng):
    """State resets at packed-segment starts: two packed sequences in one row
    must equal the same sequences run in separate rows."""
    cell = nn.LSTMCell(5, use_peepholes=False)
    rnn = nn.RNN(cell)
    a = jax.random.normal(rng, (1, 2, 3))
    bx = jax.random.normal(jax.random.fold_in(rng, 1), (1, 3, 3))
    packed = jnp.concatenate([a, bx], axis=1)           # [1, 5, 3]
    seg_starts = jnp.array([[1, 0, 1, 0, 0]], jnp.float32)
    vs = rnn.init(rng, packed, segment_starts=seg_starts)
    out_packed, _ = rnn.apply(vs, packed, segment_starts=seg_starts)
    out_a, _ = rnn.apply(vs, a)
    out_b, _ = rnn.apply(vs, bx)
    np.testing.assert_allclose(np.asarray(out_packed[0, :2]),
                               np.asarray(out_a[0]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_packed[0, 2:]),
                               np.asarray(out_b[0]), rtol=1e-5)


def test_rnn_segment_reset_reversed(rng):
    """Reversed packed rows: the reversed scan must reset state when entering
    each segment from its END, so packed == per-segment also for reverse=True
    (the BiRNN backward pass over packed rows)."""
    cell = nn.LSTMCell(5, use_peepholes=False)
    rnn = nn.RNN(cell, reverse=True)
    a = jax.random.normal(rng, (1, 2, 3))
    bx = jax.random.normal(jax.random.fold_in(rng, 1), (1, 3, 3))
    packed = jnp.concatenate([a, bx], axis=1)           # [1, 5, 3]
    seg_starts = jnp.array([[1, 0, 1, 0, 0]], jnp.float32)
    vs = rnn.init(rng, packed, segment_starts=seg_starts)
    out_packed, _ = rnn.apply(vs, packed, segment_starts=seg_starts)
    out_a, _ = rnn.apply(vs, a)
    out_b, _ = rnn.apply(vs, bx)
    np.testing.assert_allclose(np.asarray(out_packed[0, :2]),
                               np.asarray(out_a[0]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_packed[0, 2:]),
                               np.asarray(out_b[0]), rtol=1e-5)


def test_bidirectional(rng):
    bi = nn.BiRNN(nn.GRUCell(4), nn.GRUCell(4))
    x = jax.random.normal(rng, (2, 6, 3))
    vs = bi.init(rng, x)
    assert bi.apply(vs, x).shape == (2, 6, 8)


def test_rnn_grad_flows(rng):
    rnn = nn.RNN(nn.LSTMCell(4))
    x = jax.random.normal(rng, (2, 3, 3))
    vs = rnn.init(rng, x)

    def loss(p):
        out, _ = rnn.apply({"params": p, "state": {}}, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(vs["params"])
    total = sum(float(jnp.abs(l).sum()) for l in jax.tree_util.tree_leaves(g))
    assert total > 0


# ---------------------------------------------------------------- sequence ops

def test_seq_pool_kinds():
    x = jnp.array([[[1.0], [2.0], [3.0]], [[4.0], [5.0], [6.0]]])
    lengths = jnp.array([2, 3])
    np.testing.assert_allclose(np.asarray(sq.seq_pool(x, lengths, "sum")),
                               [[3.0], [15.0]])
    np.testing.assert_allclose(np.asarray(sq.seq_pool(x, lengths, "average")),
                               [[1.5], [5.0]])
    np.testing.assert_allclose(np.asarray(sq.seq_pool(x, lengths, "max")),
                               [[2.0], [6.0]])
    np.testing.assert_allclose(np.asarray(sq.seq_last(x, lengths)),
                               [[2.0], [6.0]])
    np.testing.assert_allclose(np.asarray(sq.seq_first(x, lengths)),
                               [[1.0], [4.0]])


def test_seq_concat_and_expand():
    a = jnp.arange(4.0).reshape(2, 2, 1)
    b = jnp.arange(10.0, 16.0).reshape(2, 3, 1)
    out, lens = sq.seq_concat(a, jnp.array([1, 2]), b, jnp.array([3, 1]))
    np.testing.assert_array_equal(np.asarray(lens), [4, 3])
    np.testing.assert_allclose(np.asarray(out[0, :4, 0]), [0, 10, 11, 12])
    np.testing.assert_allclose(np.asarray(out[1, :3, 0]), [2, 3, 13])
    v = jnp.array([[7.0], [9.0]])
    e = sq.seq_expand(v, jnp.array([2, 1]), 3)
    np.testing.assert_allclose(np.asarray(e[:, :, 0]),
                               [[7, 7, 0], [9, 0, 0]])


def test_kmax_and_maxid():
    s = jnp.array([[0.1, 0.9, 0.5, 0.7]])
    idx = sq.kmax_scores(s, jnp.array([3]), 2)
    assert set(np.asarray(idx[0]).tolist()) == {1, 2}
    assert int(sq.max_id(s)[0]) == 1


# ---------------------------------------------------------------- CRF oracle

def brute_force_crf(emissions, tags_all, start, stop, trans, length):
    """Enumerate all paths for log Z."""
    L = emissions.shape[-1]
    scores = []
    gold = None
    for path in itertools.product(range(L), repeat=length):
        s = start[path[0]] + emissions[0, path[0]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]] + emissions[t, path[t]]
        s += stop[path[-1]]
        scores.append(s)
    return np.logaddexp.reduce(scores)


def test_crf_loss_matches_bruteforce(rng, nprng):
    B, T, L = 2, 4, 3
    em = nprng.randn(B, T, L).astype(np.float32)
    w = nprng.randn(L + 2, L).astype(np.float32) * 0.5
    tags = nprng.randint(0, L, (B, T)).astype(np.int32)
    lengths = np.array([4, 2], np.int32)
    nll = np.asarray(crf_log_likelihood(jnp.asarray(em), jnp.asarray(tags),
                                        jnp.asarray(lengths), jnp.asarray(w)))
    for b in range(B):
        Lb = lengths[b]
        logz = brute_force_crf(em[b], None, w[0], w[1], w[2:], Lb)
        gold = w[0][tags[b, 0]] + em[b, 0, tags[b, 0]]
        for t in range(1, Lb):
            gold += w[2:][tags[b, t - 1], tags[b, t]] + em[b, t, tags[b, t]]
        gold += w[1][tags[b, Lb - 1]]
        np.testing.assert_allclose(nll[b], logz - gold, rtol=1e-4)


def test_crf_decode_matches_bruteforce(nprng):
    T, L = 5, 3
    em = nprng.randn(1, T, L).astype(np.float32)
    w = nprng.randn(L + 2, L).astype(np.float32)
    lengths = np.array([T], np.int32)
    got = np.asarray(crf_decode(jnp.asarray(em), jnp.asarray(lengths),
                                jnp.asarray(w)))[0]
    best, best_s = None, -np.inf
    for path in itertools.product(range(L), repeat=T):
        s = w[0][path[0]] + em[0, 0, path[0]]
        for t in range(1, T):
            s += w[2:][path[t - 1], path[t]] + em[0, t, path[t]]
        s += w[1][path[-1]]
        if s > best_s:
            best, best_s = path, s
    np.testing.assert_array_equal(got, best)


def test_crf_grad_is_finite(rng, nprng):
    em = jnp.asarray(nprng.randn(2, 4, 3), jnp.float32)
    tags = jnp.zeros((2, 4), jnp.int32)
    lengths = jnp.array([4, 3])
    crf = nn.CRF(3)
    vs = crf.init(rng, em, tags, lengths)

    def loss(p):
        return crf.apply({"params": p, "state": {}}, em, tags, lengths).sum()

    g = jax.tree_util.tree_leaves(jax.grad(loss)(vs["params"]))[0]
    assert np.isfinite(np.asarray(g)).all()


# ---------------------------------------------------------------- CTC oracle

def brute_force_ctc(log_probs, label, T, blank=0):
    """Sum over all alignments: enumerate all T-length paths, collapse, match."""
    V = log_probs.shape[-1]
    total = -np.inf
    for path in itertools.product(range(V), repeat=T):
        # collapse
        col = []
        prev = None
        for p in path:
            if p != prev and p != blank:
                col.append(p)
            prev = p
        if col == list(label):
            s = sum(log_probs[t, path[t]] for t in range(T))
            total = np.logaddexp(total, s)
    return -total


def test_ctc_matches_bruteforce(nprng):
    T, V = 4, 3
    logits = nprng.randn(1, T, V).astype(np.float32)
    lp = jax.nn.log_softmax(jnp.asarray(logits), -1)
    label = [1, 2]
    loss = float(ctc_loss(lp, jnp.array([T]), jnp.array([label]),
                          jnp.array([2]))[0])
    want = brute_force_ctc(np.asarray(lp[0]), label, T)
    np.testing.assert_allclose(loss, want, rtol=1e-4)


def test_ctc_repeated_label(nprng):
    T, V = 5, 3
    lp = jax.nn.log_softmax(jnp.asarray(nprng.randn(1, T, V), jnp.float32), -1)
    label = [1, 1]  # repeated label requires a blank between
    loss = float(ctc_loss(lp, jnp.array([T]), jnp.array([label]),
                          jnp.array([2]))[0])
    want = brute_force_ctc(np.asarray(lp[0]), label, T)
    np.testing.assert_allclose(loss, want, rtol=1e-4)


def test_ctc_batch_and_varlen(nprng):
    T, V, U = 6, 4, 3
    lp = jax.nn.log_softmax(jnp.asarray(nprng.randn(3, T, V), jnp.float32), -1)
    labels = jnp.array([[1, 2, 3], [2, 2, 0], [1, 0, 0]])
    in_len = jnp.array([6, 5, 3])
    lab_len = jnp.array([3, 2, 1])
    losses = np.asarray(ctc_loss(lp, in_len, labels, lab_len))
    assert np.isfinite(losses).all()
    for b, (il, ll) in enumerate([(6, 3), (5, 2), (3, 1)]):
        want = brute_force_ctc(np.asarray(lp[b, :il]),
                               list(np.asarray(labels[b, :ll])), il)
        np.testing.assert_allclose(losses[b], want, rtol=1e-3)


def test_ctc_grad_finite(nprng):
    lp_logits = jnp.asarray(nprng.randn(2, 5, 4), jnp.float32)

    def loss(z):
        lp = jax.nn.log_softmax(z, -1)
        return ctc_loss(lp, jnp.array([5, 4]), jnp.array([[1, 2], [3, 0]]),
                        jnp.array([2, 1])).sum()

    g = jax.grad(loss)(lp_logits)
    assert np.isfinite(np.asarray(g)).all()


def test_ctc_greedy_decode():
    # frames argmax: [1, 1, 0, 2, 2] -> collapse -> [1, 2]
    lp = jnp.log(jnp.asarray([[
        [0.1, 0.8, 0.1], [0.1, 0.8, 0.1], [0.8, 0.1, 0.1],
        [0.1, 0.1, 0.8], [0.1, 0.1, 0.8]]]))
    dec, lens = ctc_greedy_decode(lp, jnp.array([5]))
    assert int(lens[0]) == 2
    np.testing.assert_array_equal(np.asarray(dec[0, :2]), [1, 2])


# ---------------------------------------------------------------- attention

def test_additive_attention_masks(rng):
    att = nn.AdditiveAttention(8)
    dec = jax.random.normal(rng, (2, 6))
    enc = jax.random.normal(rng, (2, 5, 7))
    mask = jnp.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
    vs = att.init(rng, dec, enc, mask)
    ctx, w = att.apply(vs, dec, enc, mask)
    assert ctx.shape == (2, 7)
    np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(w[0, 3:]), 0.0)


def test_multihead_attention_causal(rng):
    mha = nn.MultiHeadAttention(num_heads=2)
    x = jax.random.normal(rng, (1, 4, 8))
    causal = jnp.tril(jnp.ones((4, 4)))[None]
    vs = mha.init(rng, x, mask=causal)
    out = mha.apply(vs, x, mask=causal)
    assert out.shape == (1, 4, 8)
    # causality: output at t=0 must not depend on x at t>0
    x2 = x.at[:, 2:].set(0.0)
    out2 = mha.apply(vs, x2, mask=causal)
    np.testing.assert_allclose(np.asarray(out[:, :2]), np.asarray(out2[:, :2]),
                               rtol=1e-4)
