"""Seq2seq + tagging model tests: training reduces loss, beam search decodes
the learned mapping (the analog of test_recurrent_machine_generation golden
tests), CRF taggers learn synthetic transitions."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu import data, optim
from paddle_tpu.data import datasets
from paddle_tpu.models import Seq2SeqAttention, RnnCrfTagger, LinearCrfTagger
from paddle_tpu.models.seq2seq import BOS, EOS, PAD
from paddle_tpu.train import Trainer


def nmt_batches(batch_size=32, n=256, max_len=8, vocab=50):
    """Tiny copy-task NMT data: target = source (easy to learn fast)."""
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(n // batch_size):
            lens = rng.randint(2, max_len - 1, size=batch_size)
            src = np.zeros((batch_size, max_len), np.int32)
            tgt = np.zeros((batch_size, max_len + 1), np.int32)
            for i, L in enumerate(lens):
                toks = rng.randint(3, vocab, size=L)
                src[i, :L] = toks
                tgt[i, 0] = BOS
                tgt[i, 1:L + 1] = toks
                # append EOS if room
                if L + 1 <= max_len:
                    tgt[i, L + 1 if L + 1 <= max_len else L] = EOS
            yield {"src": src, "src_len": lens.astype(np.int32),
                   "tgt": tgt, "tgt_len": (lens + 2).astype(np.int32)}
    return reader


@pytest.fixture(scope="module")
def trained_nmt():
    model = Seq2SeqAttention(50, 50, emb_dim=32, hidden=64)
    tr = Trainer(model=model,
                 loss_fn=lambda out, b: out,   # model returns per-example loss
                 optimizer=optim.adam(5e-3),
                 forward=lambda m, v, b, train, rngs: (m.apply(v, b), v["state"]))
    reader = nmt_batches()
    tr.init(jax.random.PRNGKey(0), next(iter(reader())))
    costs = []
    from paddle_tpu.train import events as ev

    def handler(e):
        if isinstance(e, ev.EndPass):
            costs.append(e.metrics["mean_cost"])

    tr.train(reader, num_passes=30, event_handler=handler)
    return model, tr, costs


def test_nmt_loss_decreases(trained_nmt):
    _, _, costs = trained_nmt
    assert costs[-1] < 0.25 * costs[0], costs


def test_beam_search_decodes_copy_task(trained_nmt):
    model, tr, _ = trained_nmt
    variables = {"params": tr.train_state.params, "state": tr.train_state.state}
    rng = np.random.RandomState(7)
    L = 4
    src = np.zeros((2, 8), np.int32)
    toks = [rng.randint(3, 50, size=L) for _ in range(2)]
    for i in range(2):
        src[i, :L] = toks[i]
    tokens, scores = model.generate(variables, jnp.asarray(src),
                                    jnp.asarray([L, L]), beam_size=3,
                                    max_len=8)
    assert tokens.shape == (2, 3, 8)
    # best beam reproduces the source prefix
    for i in range(2):
        got = np.asarray(tokens[i, 0])
        np.testing.assert_array_equal(got[:L], toks[i])
    # scores sorted best-first
    assert (np.diff(np.asarray(scores), axis=1) <= 1e-5).all()


def test_beam_is_jittable(trained_nmt):
    model, tr, _ = trained_nmt
    variables = {"params": tr.train_state.params, "state": tr.train_state.state}

    @jax.jit
    def gen(src, src_len):
        return model.generate(variables, src, src_len, beam_size=2, max_len=6)

    t, s = gen(jnp.ones((1, 8), jnp.int32) * 5, jnp.asarray([3]))
    assert t.shape == (1, 2, 6)


def tagging_batches(batch_size=32, n=512, max_len=12, vocab=100, n_tags=4):
    """Tags depend on token value range — learnable by emissions alone; a
    sticky-previous rule adds transition structure for the CRF."""
    rng = np.random.RandomState(1)

    def reader():
        for _ in range(n // batch_size):
            lens = rng.randint(3, max_len, size=batch_size)
            toks = np.zeros((batch_size, max_len), np.int32)
            tags = np.zeros((batch_size, max_len), np.int32)
            for i, L in enumerate(lens):
                tk = rng.randint(0, vocab, size=L)
                toks[i, :L] = tk
                tags[i, :L] = (tk * n_tags) // vocab
            yield {"tokens": toks, "length": lens.astype(np.int32),
                   "label": tags}
    return reader


@pytest.mark.parametrize("cls", [RnnCrfTagger, LinearCrfTagger])
def test_crf_taggers_learn(cls):
    model = (cls(100, 4, emb_dim=16, hidden=32) if cls is RnnCrfTagger
             else cls(100, 4))
    tr = Trainer(model=model,
                 loss_fn=lambda out, b: out,
                 optimizer=optim.adam(1e-2 if cls is RnnCrfTagger else 3e-2),
                 forward=lambda m, v, b, train, rngs: (m.apply(v, b),
                                                       v["state"]))
    reader = tagging_batches()
    tr.init(jax.random.PRNGKey(0), next(iter(reader())))
    tr.train(reader, num_passes=4)
    variables = {"params": tr.train_state.params, "state": tr.train_state.state}
    batch = next(iter(reader()))
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    pred = model.apply(variables, batch, method="decode")
    mask = np.arange(12)[None, :] < np.asarray(batch["length"])[:, None]
    acc = (np.asarray(pred) == np.asarray(batch["label"]))[mask].mean()
    assert acc > 0.9, acc


def test_traffic_prediction_learns():
    """The traffic_prediction acceptance demo: multi-horizon speed-category
    accuracy must clearly beat the majority-class baseline."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu import data, optim
    from paddle_tpu.data import datasets
    from paddle_tpu.models import TrafficPredictor
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    reader = data.batched(
        data.map_readers(lambda s: {"x": s[0], "label": s[1]},
                         datasets.traffic("train", n=2048)), 64)
    model = TrafficPredictor()

    def loss_fn(out, b):
        # multi-task CE: average over the 24 horizons (flatten task dim)
        B, H, C = out.shape
        return costs.softmax_cross_entropy(
            out.reshape(B * H, C), b["label"].reshape(B * H)).reshape(
            B, H).mean(-1)

    tr = Trainer(model, loss_fn, optim.rmsprop(1e-3))
    tr.init(jax.random.PRNGKey(0), next(iter(reader())))
    tr.train(reader, num_passes=6, log_period=0)

    test = list(datasets.traffic("test", n=512)())
    x = jnp.asarray(np.stack([s[0] for s in test]))
    y = np.stack([s[1] for s in test])
    pred = np.argmax(np.asarray(model.apply(
        {"params": jax.device_get(tr.train_state.params)}, x)), -1)
    acc = (pred == y).mean()
    majority = max((y == c).mean() for c in range(4))
    assert acc > majority + 0.15, (acc, majority)
