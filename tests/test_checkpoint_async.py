"""Async checkpointing + crash-atomic swap (VERDICT r4 #5).

The reference keeps checkpoint work off the training hot path (Go pserver
ticker, go/pserver/service.go:119-174; ConcurrentRemoteParameterUpdater,
paddle/trainer/RemoteParameterUpdater.cpp:244) and survives crashes during
a save by writing aside then renaming over (service.go:346-420). These
tests pin both properties: training through an in-flight save is
bit-identical to synchronous saving, and a kill at ANY point inside the
atomic swap leaves a loadable pass dir.
"""

import os
import threading

import numpy as np
import jax
import pytest

from paddle_tpu import data, optim
from paddle_tpu.data import datasets
from paddle_tpu.models import MnistMLP
from paddle_tpu.nn import costs
from paddle_tpu.train import Trainer, checkpoint as ckpt


def _mnist_batches(batch_size=32, n=128):
    r = datasets.mnist("train", synthetic_n=n)
    return data.batched(
        data.map_readers(lambda s: {"x": s[0], "label": s[1]}, r), batch_size)


def _make_trainer():
    return Trainer(
        model=MnistMLP(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3))


def _train(tmp, async_, saving_period=2):
    tr = _make_trainer()
    reader = _mnist_batches()
    tr.init(jax.random.PRNGKey(0), next(iter(reader())))
    tr.train(reader, num_passes=3, checkpoint_dir=str(tmp),
             checkpoint_async=async_, saving_period=saving_period)
    return tr


def test_async_training_identical_to_sync(tmp_path):
    """Training THROUGH in-flight background saves (mid-pass saving_period
    keeps one in the air almost continuously) produces the same params and
    the same loadable checkpoints as the synchronous path."""
    tr_sync = _train(tmp_path / "sync", async_=False)
    tr_async = _train(tmp_path / "async", async_=True)
    p_sync = jax.device_get(tr_sync.train_state.params)
    p_async = jax.device_get(tr_async.train_state.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), p_sync, p_async)
    # every pass dir is complete and CRC-valid on both sides
    for root in (tmp_path / "sync", tmp_path / "async"):
        assert ckpt.latest_pass(str(root)) == 2
        for pass_id in (0, 1, 2):
            loaded = ckpt.load_checkpoint(str(root), pass_id)
            assert loaded["pass_id"] == pass_id
    a = ckpt.load_checkpoint(str(tmp_path / "sync"), 2)
    b = ckpt.load_checkpoint(str(tmp_path / "async"), 2)
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(x, y),
        a["params"], b["params"])


def test_async_error_surfaces_at_fence(tmp_path, monkeypatch):
    """A failing background write must re-raise at the next fence, not
    vanish."""
    saver = ckpt.AsyncCheckpointer()

    def boom(*a, **k):
        raise OSError("disk full")
    monkeypatch.setattr(ckpt, "_write_pass_dir", boom)
    try:
        saver.save(str(tmp_path), 0, {"params": {"w": np.ones((2,))}})
        with pytest.raises(OSError, match="disk full"):
            saver.wait()
    finally:
        saver.close()


@pytest.mark.parametrize("crash_at", [1, 2])
def test_kill_inside_swap_always_leaves_loadable_dir(tmp_path, monkeypatch,
                                                     crash_at):
    """Overwrite pass-00000 (v1 -> v2) with a crash injected at each rename
    of the swap: (1) live -> .old, (2) .tmp -> live. Afterwards
    load_checkpoint must succeed with v1 or v2 content — never nothing.
    The old recipe (rmtree live, then rename) fails this for crash_at=2."""
    root = str(tmp_path)
    v1 = {"params": {"w": np.full((4,), 1.0)}}
    v2 = {"params": {"w": np.full((4,), 2.0)}}
    ckpt.save_checkpoint(root, 0, v1)

    real = os.rename
    count = {"n": 0}

    def boom(src, dst):
        count["n"] += 1
        if count["n"] == crash_at:
            raise RuntimeError("simulated crash inside atomic swap")
        return real(src, dst)
    monkeypatch.setattr(ckpt.os, "rename", boom)
    with pytest.raises(RuntimeError, match="simulated crash"):
        ckpt.save_checkpoint(root, 0, v2)
    monkeypatch.setattr(ckpt.os, "rename", real)

    # recovery on read: some complete version must load
    assert ckpt.latest_pass(root) == 0
    out = ckpt.load_checkpoint(root, 0)
    w = np.asarray(out["params"]["w"])
    assert w[0] in (1.0, 2.0), w


def test_incomplete_tmp_never_adopted(tmp_path):
    """A half-written .tmp (no valid manifest) from a mid-write crash must
    not shadow or replace anything."""
    root = str(tmp_path)
    ckpt.save_checkpoint(root, 0, {"params": {"w": np.arange(3.0)}})
    stray = os.path.join(root, "pass-00001.tmp")
    os.makedirs(stray)
    with open(os.path.join(stray, "params.npz"), "wb") as f:
        f.write(b"garbage")
    assert ckpt.latest_pass(root) == 0
    out = ckpt.load_checkpoint(root)
    np.testing.assert_allclose(out["params"]["w"], np.arange(3.0))
    assert os.path.isdir(stray)        # left for inspection, not adopted


def test_gc_prunes_stale_siblings_keeps_crashed_latest(tmp_path):
    """.old/.tmp leftovers are readable fallbacks while in retention, are
    pruned once their pass falls out of retention (so a deleted pass can
    never be resurrected from a stale sibling), and a crashed LATEST save
    (leftover newer than every live pass) is always kept."""
    root = str(tmp_path)
    for i in range(4):
        ckpt.save_checkpoint(root, i, {"params": {"w": np.full((2,), i)}},
                             keep_last=10)
    # crash leftover for pass 0 (reads resolve it; no rename happens)
    os.rename(os.path.join(root, "pass-00000"),
              os.path.join(root, "pass-00000.old"))
    assert ckpt.latest_pass(root) == 3
    out = ckpt.load_checkpoint(root, 0)          # resolved from .old
    np.testing.assert_allclose(out["params"]["w"], np.zeros((2,)))
    assert not os.path.isdir(os.path.join(root, "pass-00000"))  # pure read
    # crashed latest: complete .tmp newer than every live pass
    ckpt._write_pass_dir(root, 5, {"params": {"w": np.full((2,), 5.0)}})
    os.rename(os.path.join(root, "pass-00005"),
              os.path.join(root, "pass-00005.tmp"))
    # stray non-numeric dir must neither crash _gc nor be deleted
    os.makedirs(os.path.join(root, "pass-backup"))
    ckpt._gc(root, keep_last=2)
    left = sorted(d for d in os.listdir(root) if d.startswith("pass-"))
    # newest 2 READABLE passes survive — the crashed latest (.tmp) counts
    # as a real pass; pass 0's stale .old went with its pass
    assert left == ["pass-00003", "pass-00005.tmp", "pass-backup"]
    assert ckpt.latest_pass(root) == 5
    out = ckpt.load_checkpoint(root)
    np.testing.assert_allclose(out["params"]["w"], np.full((2,), 5.0))


def test_rewrite_of_crash_surviving_tmp_keeps_a_complete_copy(tmp_path,
                                                              monkeypatch):
    """If a pass survives ONLY as .tmp (crash between renames) and is then
    re-saved, the rewrite must not destroy the sole copy: atomic_dir
    demotes the complete .tmp to .old, and a crash during the rewrite
    still leaves a loadable pass."""
    root = str(tmp_path)
    ckpt._write_pass_dir(root, 0, {"params": {"w": np.full((2,), 1.0)}})
    os.rename(os.path.join(root, "pass-00000"),
              os.path.join(root, "pass-00000.tmp"))
    assert ckpt.latest_pass(root) == 0          # readable via .tmp

    # crash the re-save before ANY rename lands (np.savez blows up)
    def boom(*a, **k):
        raise RuntimeError("simulated crash mid-write")
    monkeypatch.setattr(ckpt.np, "savez", boom)
    with pytest.raises(RuntimeError, match="mid-write"):
        ckpt.save_checkpoint(root, 0, {"params": {"w": np.full((2,), 2.0)}})
    monkeypatch.undo()

    out = ckpt.load_checkpoint(root, 0)         # v1 survived as .old
    np.testing.assert_allclose(out["params"]["w"], np.full((2,), 1.0))


def test_checkpoint_telemetry_record_per_save(tmp_path):
    """ISSUE 10 satellite: each landed async save emits one
    kind="checkpoint" record — pass_id, snapshot/write wall, bytes on
    disk, and the backlog wait behind the previous in-flight write."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    mem = InMemorySink()
    tel = Telemetry(sinks=[mem], health=False, memory=False)
    with ckpt.AsyncCheckpointer(telemetry=tel) as saver:
        for i in range(2):
            saver.save(str(tmp_path), i,
                       {"params": {"w": np.ones((64,), np.float32) * i}})
        saver.wait()
    recs = mem.by_kind("checkpoint")
    assert [r["pass_id"] for r in recs] == [0, 1]
    for r in recs:
        assert r["snapshot_ms"] >= 0 and r["write_ms"] >= 0
        assert r["bytes"] > 0 and r["backlog_ms"] >= 0
        assert r["async"] is True
    assert tel.summary()["background_failures"] == 0


def test_background_failure_counts_and_reraises(tmp_path, monkeypatch):
    """A failing background write bumps telemetry.background_failures
    (visible in summary() even if the fence is never reached) AND still
    re-raises at the fence."""
    from paddle_tpu.obs import InMemorySink, Telemetry
    tel = Telemetry(sinks=[InMemorySink()], health=False, memory=False)
    saver = ckpt.AsyncCheckpointer(telemetry=tel)

    def boom(*a, **k):
        raise OSError("disk full")
    monkeypatch.setattr(ckpt, "_write_pass_dir", boom)
    try:
        saver.save(str(tmp_path), 0, {"params": {"w": np.ones((2,))}})
        with pytest.raises(OSError, match="disk full"):
            saver.wait()
    finally:
        saver.close()
    assert tel.background_failures == 1
    assert tel.summary()["background_failures"] == 1
    assert len(tel.sinks[0].by_kind("checkpoint")) == 0   # no record


def test_atexit_final_wait_fences_inflight_write(tmp_path):
    """Interpreter-exit safety: the registered atexit hook fences the
    in-flight write (no truncation), and close() unregisters it."""
    import atexit
    saver = ckpt.AsyncCheckpointer()
    gate = threading.Event()
    real_write = ckpt._write_pass_dir

    def slow_write(*a, **k):
        gate.wait(timeout=10)
        return real_write(*a, **k)
    ckpt._write_pass_dir = slow_write
    try:
        saver.save(str(tmp_path), 0, {"params": {"w": np.ones((2,))}})
        gate.set()
        saver._atexit_wait()           # what interpreter exit would run
        assert ckpt.latest_pass(str(tmp_path)) == 0
    finally:
        ckpt._write_pass_dir = real_write
        saver.close()
    # close() unregistered the hook: re-unregistering finds nothing
    atexit.unregister(saver._atexit_wait)   # no-op, must not raise


def test_async_overlaps_with_training_thread(tmp_path):
    """The background write really runs concurrently: a slow write does not
    block the caller between saves (smoke check that save() returns before
    the write lands)."""
    saver = ckpt.AsyncCheckpointer()
    gate = threading.Event()
    real_write = ckpt._write_pass_dir

    def slow_write(*a, **k):
        gate.wait(timeout=10)
        return real_write(*a, **k)
    ckpt._write_pass_dir = slow_write
    try:
        saver.save(str(tmp_path), 0, {"params": {"w": np.ones((2,))}})
        # save() returned while the write is gated: nothing on disk yet
        assert ckpt.latest_pass(str(tmp_path)) is None
        gate.set()
        saver.wait()
        assert ckpt.latest_pass(str(tmp_path)) == 0
    finally:
        ckpt._write_pass_dir = real_write
        saver.close()
