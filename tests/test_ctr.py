"""Sparse/CTR path tests — the analog of the reference's quick_start sparse
demo + SparseRemoteParameterUpdater tests (``test_CompareSparse.cpp``:
local-vs-remote == replicated-vs-row-sharded here)."""

import numpy as np
import jax
import pytest

import paddle_tpu as pt
from paddle_tpu import data, optim
from paddle_tpu.data import datasets
from paddle_tpu.models import CTR_SHARDING_RULES, SparseLR, WideDeepCTR
from paddle_tpu.nn import costs
from paddle_tpu.train import Auc, Trainer

FIELDS, VOCAB = 8, 50


def ctr_batches(split, batch_size=256, **kw):
    r = datasets.synthetic_ctr(split, num_fields=FIELDS,
                               vocab_per_field=VOCAB, **kw)
    return data.batched(
        data.map_readers(lambda s: {"x": s[0], "label": s[1]}, r), batch_size)


def _make_trainer(model, mesh=None, param_sharding=None, lr=0.5,
                  donate=True):
    return Trainer(
        model=model,
        loss_fn=lambda out, b: costs.binary_logistic(out, b["label"]),
        optimizer=optim.ftrl(lr, lambda1=0.01, lambda2=0.01),
        mesh=mesh or pt.make_mesh({"data": 8}),
        evaluator=Auc(from_logits=True),
        param_sharding=param_sharding, donate=donate)


def test_sparse_lr_ftrl_reaches_auc(rng):
    """Wide LR + FTRL on the synthetic CTR task reaches AUC > 0.75 — the
    quick_start ``trainer_config.lr.py`` acceptance run."""
    trainer = _make_trainer(SparseLR(FIELDS, VOCAB))
    sample = next(ctr_batches("train")())
    trainer.init(rng, sample)
    trainer.train(ctr_batches("train"), num_passes=3, log_period=0)
    _, metrics = trainer.evaluate(ctr_batches("test"))
    assert metrics["auc"] > 0.75, metrics


def test_wide_deep_trains(rng):
    trainer = _make_trainer(WideDeepCTR(FIELDS, VOCAB, emb_dim=8,
                                        hidden=(32,)), lr=0.2)
    sample = next(ctr_batches("train")())
    trainer.init(rng, sample)
    trainer.train(ctr_batches("train", n=4096), num_passes=2, log_period=0)
    _, metrics = trainer.evaluate(ctr_batches("test"))
    assert metrics["auc"] > 0.7, metrics


def test_sharded_table_matches_replicated(rng):
    """Row-sharded embedding tables over the model axis == replicated table
    (the local-vs-remote equivalence of test_CompareSparse.cpp:144)."""
    batches = list(data.firstn(ctr_batches("train"), 5)())

    def run(mesh, sharding):
        trainer = _make_trainer(WideDeepCTR(FIELDS, VOCAB, emb_dim=8,
                                            hidden=(32,)),
                                mesh=mesh, param_sharding=sharding,
                                donate=False, lr=0.2)
        trainer.init(jax.random.PRNGKey(3), batches[0])
        trainer._build_train_step()
        ts = trainer.train_state
        p, s, o, st = ts.params, ts.state, ts.opt_state, ts.step
        losses = []
        for hb in batches:
            b = trainer._shard(hb)
            p, s, o, st, loss, stats = trainer._train_step(
                p, s, o, st, b, jax.random.PRNGKey(9))
            losses.append(float(loss))
        return losses, p

    l_rep, p_rep = run(pt.make_mesh({"data": 8}), None)
    l_sh, p_sh = run(pt.make_mesh({"data": 2, "model": 4}),
                     CTR_SHARDING_RULES)
    np.testing.assert_allclose(l_rep, l_sh, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_rep),
                    jax.tree_util.tree_leaves(p_sh)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
    # and the tables really are row-sharded
    root = next(iter(p_sh))
    assert tuple(p_sh[root]["deep"]["w"].sharding.spec) == ("model", None)
