"""Sparse/CTR path tests — the analog of the reference's quick_start sparse
demo + SparseRemoteParameterUpdater tests (``test_CompareSparse.cpp``:
local-vs-remote == replicated-vs-row-sharded here)."""

import numpy as np
import jax
import pytest

import paddle_tpu as pt
from paddle_tpu import data, optim
from paddle_tpu.data import datasets
from paddle_tpu.models import CTR_SHARDING_RULES, SparseLR, WideDeepCTR
from paddle_tpu.nn import costs
from paddle_tpu.train import Auc, Trainer

FIELDS, VOCAB = 8, 50


def ctr_batches(split, batch_size=256, **kw):
    r = datasets.synthetic_ctr(split, num_fields=FIELDS,
                               vocab_per_field=VOCAB, **kw)
    return data.batched(
        data.map_readers(lambda s: {"x": s[0], "label": s[1]}, r), batch_size)


def _make_trainer(model, mesh=None, param_sharding=None, lr=0.5,
                  donate=True):
    return Trainer(
        model=model,
        loss_fn=lambda out, b: costs.binary_logistic(out, b["label"]),
        optimizer=optim.ftrl(lr, lambda1=0.01, lambda2=0.01),
        mesh=mesh or pt.make_mesh({"data": 8}),
        evaluator=Auc(from_logits=True),
        param_sharding=param_sharding, donate=donate)


def test_sparse_lr_ftrl_reaches_auc(rng):
    """Wide LR + FTRL on the synthetic CTR task reaches AUC > 0.75 — the
    quick_start ``trainer_config.lr.py`` acceptance run."""
    trainer = _make_trainer(SparseLR(FIELDS, VOCAB))
    sample = next(ctr_batches("train")())
    trainer.init(rng, sample)
    trainer.train(ctr_batches("train"), num_passes=3, log_period=0)
    _, metrics = trainer.evaluate(ctr_batches("test"))
    assert metrics["auc"] > 0.75, metrics


def test_wide_deep_trains(rng):
    trainer = _make_trainer(WideDeepCTR(FIELDS, VOCAB, emb_dim=8,
                                        hidden=(32,)), lr=0.2)
    sample = next(ctr_batches("train")())
    trainer.init(rng, sample)
    trainer.train(ctr_batches("train", n=4096), num_passes=2, log_period=0)
    _, metrics = trainer.evaluate(ctr_batches("test"))
    assert metrics["auc"] > 0.7, metrics


def test_sharded_table_matches_replicated(rng):
    """Row-sharded embedding tables over the model axis == replicated table
    (the local-vs-remote equivalence of test_CompareSparse.cpp:144)."""
    batches = list(data.firstn(ctr_batches("train"), 5)())

    def run(mesh, sharding):
        trainer = _make_trainer(WideDeepCTR(FIELDS, VOCAB, emb_dim=8,
                                            hidden=(32,)),
                                mesh=mesh, param_sharding=sharding,
                                donate=False, lr=0.2)
        trainer.init(jax.random.PRNGKey(3), batches[0])
        trainer._build_train_step()
        ts = trainer.train_state
        p, s, o, st = ts.params, ts.state, ts.opt_state, ts.step
        losses = []
        for hb in batches:
            b = trainer._shard(hb)
            p, s, o, st, loss, stats = trainer._train_step(
                p, s, o, st, b, jax.random.PRNGKey(9))
            losses.append(float(loss))
        return losses, p

    l_rep, p_rep = run(pt.make_mesh({"data": 8}), None)
    l_sh, p_sh = run(pt.make_mesh({"data": 2, "model": 4}),
                     CTR_SHARDING_RULES)
    np.testing.assert_allclose(l_rep, l_sh, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_rep),
                    jax.tree_util.tree_leaves(p_sh)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
    # and the tables really are row-sharded
    root = next(iter(p_sh))
    assert tuple(p_sh[root]["deep"]["w"].sharding.spec) == ("model", None)


def test_sparse_float_slot_matches_dense_matmul(rng):
    """(ids, weights) is PyDataProvider2's sparse_float_vector slot
    (reference: PyDataProvider2.py:116-248): the wide logit must equal the
    dense matmul of the weighted multi-hot vector, and omitting weights
    must equal weights=1 (the sparse_binary_vector special case)."""
    import jax.numpy as jnp
    nprng = np.random.RandomState(3)
    B = 16
    ids = nprng.randint(0, VOCAB, (B, FIELDS)).astype(np.int32)
    ids[0, 2] = -1                                     # padding slot
    w = nprng.normal(size=(B, FIELDS)).astype(np.float32)

    m = SparseLR(FIELDS, VOCAB, name="lr")
    variables = m.init(rng, ids)
    table = np.asarray(variables["params"]["lr"]["wide"]["w"])   # [F*V, 1]
    bias = float(np.asarray(variables["params"]["lr"]["b"]))

    got = np.asarray(m.apply(variables, ids, weights=jnp.asarray(w)))
    dense_x = np.zeros((B, FIELDS * VOCAB), np.float32)
    for b in range(B):
        for f in range(FIELDS):
            if ids[b, f] >= 0:
                dense_x[b, f * VOCAB + ids[b, f]] += w[b, f]
    oracle = dense_x @ table[:, 0] + bias
    np.testing.assert_allclose(got, oracle, rtol=1e-5, atol=1e-6)

    # binary case: no weights == all-ones weights
    ones = np.asarray(m.apply(variables, ids,
                              weights=jnp.ones_like(jnp.asarray(w))))
    none = np.asarray(m.apply(variables, ids))
    np.testing.assert_allclose(none, ones, rtol=1e-6)


def test_wide_deep_sparse_float_slot(rng):
    """WideDeepCTR's weighted lookup == manual weighted gather from its
    own tables (deep fields scale by the value, the dense-matmul view of
    the sparse_float slot feeding an embedding layer)."""
    import jax.numpy as jnp
    nprng = np.random.RandomState(4)
    B, D = 8, 4
    ids = nprng.randint(0, VOCAB, (B, FIELDS)).astype(np.int32)
    ids[1, 0] = -1
    w = nprng.normal(size=(B, FIELDS)).astype(np.float32)
    m = WideDeepCTR(FIELDS, VOCAB, emb_dim=D, hidden=(8,), name="wd")
    variables = m.init(rng, ids)
    p = variables["params"]["wd"]

    got = np.asarray(m.apply(variables, ids, weights=jnp.asarray(w)))

    wide_t = np.asarray(p["wide"]["w"])                # [F*V, 1]
    deep_t = np.asarray(p["deep"]["w"])                # [F*V, D]
    wide_logit = np.zeros(B, np.float32)
    flat = np.zeros((B, FIELDS * D), np.float32)
    for b in range(B):
        for f in range(FIELDS):
            if ids[b, f] >= 0:
                gidx = f * VOCAB + ids[b, f]
                wide_logit[b] += w[b, f] * wide_t[gidx, 0]
                flat[b, f * D:(f + 1) * D] = w[b, f] * deep_t[gidx]
    # deep head: run the model's own mlp on the oracle-weighted features
    h = np.maximum(flat @ np.asarray(p["mlp"]["fc0"]["w"])
                   + np.asarray(p["mlp"]["fc0"]["b"]), 0.0)
    deep_logit = (h @ np.asarray(p["mlp"]["out"]["w"])
                  + np.asarray(p["mlp"]["out"]["b"]))[:, 0]
    np.testing.assert_allclose(got, wide_logit + deep_logit,
                               rtol=1e-4, atol=1e-5)
