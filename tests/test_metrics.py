"""Fleet-wide metrics backbone tests (ISSUE 19): the typed registry
(Counter/Gauge/Histogram over bounded ring-buffer series), Prometheus
text exposition round-trip, the cross-host delta-merge protocol (and
its SIGKILL-loss semantics), default-off invisibility through a live
in-process fleet twin drill, the report's registry read-through for
transport totals, the ``obs.top`` sparkline dashboard block, and the
P² quantile adversarial streams (satellite 4).

Fleet drills are in-process on a :class:`SimClock` — the process/socket
twin with real piggybacked deltas runs in ``bench.py --fleet-child``
leg 4."""

import json
import os
import tempfile

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models import TransformerLM
from paddle_tpu.obs import InMemorySink, P2Quantile, Telemetry
from paddle_tpu.obs import report as report_lib
from paddle_tpu.obs import top as top_lib
from paddle_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                    MetricsHub, log_buckets,
                                    parse_exposition)
from paddle_tpu.obs.percentiles import percentile
from paddle_tpu.serve import ServingFleet, SimClock
from paddle_tpu.serve.loadgen import make_workload

V, W = 64, 24
DT = 0.1


@pytest.fixture(scope="module")
def model_and_vars():
    model = TransformerLM(vocab=V, dim=16, num_layers=1, num_heads=2,
                          ffn_hidden=32, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))
    return model, vs


@pytest.fixture(scope="module")
def fleet_runs(model_and_vars):
    """One instrumented + one dark fleet twin, played ONCE and shared
    by every fleet-level test below (the drills dominate this module's
    runtime; the assertions are all on the captured evidence)."""
    model, vs = model_and_vars
    runs = {}
    for on in (True, False):
        mem = InMemorySink()
        f = _fleet(model, vs, 2, metrics=on,
                   telemetry=Telemetry(sinks=[mem]))
        try:
            wl = _workload()
            frs = f.play(wl, dt_s=DT)
            f.emit_stats()
            stats = f.stats()
            runs[on] = {
                "n_requests": len(wl),
                "tokens": {fr.rid: (fr.finish_reason, list(fr.tokens))
                           for fr in frs},
                "stats_keys": set(stats),
                "transport": stats["transport"],
                "hub": f.metrics,
                "records": list(mem.records),
            }
        finally:
            f.shutdown()
    return runs


def _fleet(model, vs, n, **kw):
    return ServingFleet.from_model(
        model, vs, n, engine_kwargs=dict(max_slots=2, block_size=4),
        clock=SimClock(), heartbeat_timeout_s=0.25, est_tick_s=DT,
        root=tempfile.mkdtemp(prefix="paddle_tpu_metrics_"), **kw)


def _workload(n=6, seed=7):
    return make_workload(n, V, seed=seed, rate_rps=30.0,
                         prompt_len=(2, 6), max_new=(3, 8), max_total=W)


def _ticking_hub(retention=512):
    """A hub on a fake clock that advances 1s per stamp — deterministic
    timestamps without SimClock plumbing."""
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    return MetricsHub(clock=clock, retention=retention)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_is_monotone():
    hub = _ticking_hub()
    c = hub.counter("requests", "total requests")
    c.inc()
    c.inc(4)
    assert c.value == 5.0
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.value == 5.0            # rejected inc must not corrupt
    c.inc(0)                         # zero is a no-op, not a sample
    assert len(c.samples()) == 2


def test_gauge_last_write_wins():
    hub = _ticking_hub()
    g = hub.gauge("depth", "queue depth")
    assert g.value is None
    g.set(3)
    g.inc(2)
    g.dec()
    assert g.value == 4.0
    assert [v for _, v in g.samples()] == [3.0, 5.0, 4.0]


def test_log_buckets_policy():
    bs = log_buckets(lo=1e-3, hi=1e3, per_decade=1)
    assert bs == [1e-3, 1e-2, 1e-1, 1.0, 10.0, 100.0, 1000.0]
    assert bs == sorted(bs)
    # 6-sig-digit stability: recomputing yields identical floats
    assert log_buckets() == log_buckets()
    with pytest.raises(ValueError):
        log_buckets(lo=0.0)
    with pytest.raises(ValueError):
        log_buckets(lo=10.0, hi=1.0)


def test_histogram_bucket_math_vs_numpy():
    hub = _ticking_hub()
    h = hub.histogram("lat", "latency", buckets=[1.0, 10.0, 100.0])
    rng = np.random.RandomState(0)
    vals = rng.lognormal(mean=1.5, sigma=1.5, size=500)
    for v in vals:
        h.observe(float(v))
    # le semantics: bucket i owns v <= bound[i] (and > bound[i-1]);
    # the trailing slot is the +Inf overflow
    bounds = np.array([1.0, 10.0, 100.0])
    expect = [int(np.sum(vals <= 1.0)),
              int(np.sum((vals > 1.0) & (vals <= 10.0))),
              int(np.sum((vals > 10.0) & (vals <= 100.0))),
              int(np.sum(vals > 100.0))]
    assert h.counts == expect
    assert h.count == 500 and sum(h.counts) == 500
    assert h.sum == pytest.approx(float(np.sum(vals)))
    # a value exactly on a bound lands IN that bound's bucket
    h2 = hub.histogram("lat2", buckets=[1.0, 10.0])
    h2.observe(10.0)
    assert h2.counts == [0, 1, 0]
    with pytest.raises(ValueError):
        hub.histogram("bad", buckets=[2.0, 1.0])


def test_ring_buffer_eviction_oldest_first():
    hub = _ticking_hub(retention=4)
    c = hub.counter("ticks")
    for _ in range(7):
        c.inc()
    s = c.samples()
    assert len(s) == 4
    # cumulative values 4..7 survive; 1..3 were evicted oldest-first
    assert [v for _, v in s] == [4.0, 5.0, 6.0, 7.0]
    assert s[0][0] < s[-1][0]
    # since= filters on the stamped clock
    assert c.samples(since=s[-1][0]) == [s[-1]]


def test_label_isolation_and_type_conflict():
    hub = _ticking_hub()
    a = hub.counter("rpc", "per-link", link="0")
    b = hub.counter("rpc", "per-link", link="1")
    assert a is not b
    a.inc(3)
    assert b.value == 0.0
    assert hub.counter("rpc", link="0") is a       # get-or-create
    with pytest.raises(ValueError):
        hub.gauge("rpc", link="2")                 # kind conflict
    rows = {(r["labels"].get("link")): r["value"]
            for r in hub.snapshot() if r["name"] == "rpc"}
    assert rows == {"0": 3.0, "1": 0.0}


def test_scoped_facade_merges_labels():
    hub = _ticking_hub()
    sc = hub.scoped(replica="2").scoped(role="decode")
    sc.counter("ticks").inc()
    (row,) = hub.snapshot()
    assert row["labels"] == {"replica": "2", "role": "decode"}
    assert sc.clock is hub.clock


def test_query_label_superset():
    hub = _ticking_hub()
    hub.counter("x", a="1", b="2").inc(5)
    hub.counter("x", a="1", b="3").inc(7)
    got = hub.query("x", a="1")
    assert len(got) == 2
    got = hub.query("x", b="3")
    assert len(got) == 1 and got[0]["samples"][-1][1] == 7.0
    assert hub.query("x", a="9") == []


# ---------------------------------------------------------------------------
# Prometheus text exposition round-trip
# ---------------------------------------------------------------------------

def test_exposition_round_trip():
    hub = _ticking_hub()
    hub.counter("reqs", "total reqs", path='/v1/"gen"\\x').inc(12)
    hub.gauge("depth", "queue depth", replica="0").set(2.5)
    h = hub.histogram("lat_ms", "tick latency", buckets=[1.0, 10.0])
    for v in (0.5, 3.0, 3.0, 50.0):
        h.observe(v)
    text = hub.render()
    assert "# HELP reqs total reqs" in text
    parsed = parse_exposition(text)
    assert parsed["types"] == {"reqs": "counter", "depth": "gauge",
                               "lat_ms": "histogram"}
    samples = {(n, tuple(sorted(l.items()))): v
               for n, l, v in parsed["samples"]}
    # label escaping survives the round trip
    assert samples[("reqs",
                    (("path", '/v1/"gen"\\x'),))] == 12.0
    assert samples[("depth", (("replica", "0"),))] == 2.5
    # histogram renders CUMULATIVE le-buckets plus sum/count
    assert samples[("lat_ms_bucket", (("le", "1"),))] == 1.0
    assert samples[("lat_ms_bucket", (("le", "10"),))] == 3.0
    assert samples[("lat_ms_bucket", (("le", "+Inf"),))] == 4.0
    assert samples[("lat_ms_count", ())] == 4.0
    assert samples[("lat_ms_sum", ())] == pytest.approx(56.5)


# ---------------------------------------------------------------------------
# cross-host delta protocol
# ---------------------------------------------------------------------------

def test_delta_drain_absorb_namespaced_merge():
    child, parent = _ticking_hub(), _ticking_hub()
    child.counter("ticks").inc(3)
    child.gauge("depth").set(2)
    h = child.histogram("lat", buckets=[1.0, 10.0])
    h.observe(0.5)
    h.observe(5.0)
    batch = child.drain_delta()
    assert child.drain_delta() == []           # watermark advanced
    parent.absorb_delta(json.loads(json.dumps(batch)), replica="0")
    # second child round: only the NEW increments travel
    child.counter("ticks").inc(2)
    h.observe(100.0)
    batch2 = child.drain_delta()
    (cinc,) = [d for d in batch2 if d["kind"] == "counter"]
    assert cinc["inc"] == 2.0
    parent.absorb_delta(batch2, replica="0")
    rows = {r["name"]: r for r in parent.snapshot()}
    assert rows["ticks"]["value"] == 5.0
    assert rows["ticks"]["labels"] == {"replica": "0"}
    assert rows["lat"]["count"] == 3
    assert rows["lat"]["counts"] == [1, 1, 1]
    assert rows["lat"]["sum"] == pytest.approx(105.5)
    assert rows["depth"]["value"] == 2.0


def test_delta_lost_with_sigkilled_child_stays_lost():
    child, parent = _ticking_hub(), _ticking_hub()
    child.counter("ticks").inc(4)
    child.drain_delta()                        # shipped... and lost
    child.counter("ticks").inc(1)
    parent.absorb_delta(child.drain_delta(), replica="0")
    # the parent honestly shows only what was delivered — no
    # resynthesis of the batch that died with the process
    (row,) = [r for r in parent.snapshot() if r["name"] == "ticks"]
    assert row["value"] == 1.0


def test_histogram_merge_rejects_mismatched_buckets():
    hub = _ticking_hub()
    h = hub.histogram("lat", buckets=[1.0, 10.0])
    with pytest.raises(ValueError):
        h.merge([1, 2], 3.0, 3)                # 2 counts vs 3 slots


# ---------------------------------------------------------------------------
# fleet integration: default-off invisibility + registry contents
# ---------------------------------------------------------------------------

def test_fleet_metrics_dark_twin_identical(fleet_runs):
    runs = fleet_runs
    assert runs[True]["tokens"] == runs[False]["tokens"]
    # the registry adds ZERO new stats keys — fleet.stats() reads
    # through it, it does not grow because of it
    assert runs[True]["stats_keys"] == runs[False]["stats_keys"]
    assert runs[False]["hub"] is None
    assert runs[True]["hub"] is not None


def test_fleet_registry_contents_and_emit(fleet_runs):
    run = fleet_runs[True]
    snap = run["hub"].snapshot()
    rows = {(r["name"], tuple(sorted(r["labels"].items()))): r
            for r in snap}
    ticks = rows[("fleet_ticks", ())]
    assert ticks["type"] == "counter" and ticks["value"] > 0
    assert (rows[("fleet_requests_submitted", ())]["value"]
            == run["n_requests"])
    # per-replica namespacing from the scoped handles
    for rep in ("0", "1"):
        assert any(n == "engine_ticks"
                   and dict(l).get("replica") == rep
                   for (n, l) in rows), rep
    # router tick-duration histogram accumulates real observations
    hist = rows[("fleet_router_ms", ())]
    assert hist["type"] == "histogram"
    assert hist["count"] == ticks["value"]
    # exposition parses and types agree with the snapshot
    parsed = parse_exposition(run["hub"].render())
    assert parsed["types"]["fleet_ticks"] == "counter"
    assert parsed["types"]["fleet_router_ms"] == "histogram"
    # ring history is queryable
    (q,) = run["hub"].query("fleet_ticks")
    assert len(q["samples"]) >= 2
    # emit_stats ships one kind="metrics" snapshot record
    mets = [r for r in run["records"] if r.get("kind") == "metrics"]
    assert len(mets) == 1
    assert any(r["name"] == "fleet_ticks" for r in mets[0]["metrics"])


def test_transport_totals_read_through_matches_dark(fleet_runs):
    """Satellite 2: fleet.stats() transport totals must be identical
    whether they come from the registry (metrics on) or the legacy
    attribute counters (metrics off) — same drill, same totals."""
    assert fleet_runs[True]["transport"] == fleet_runs[False]["transport"]
    assert set(fleet_runs[True]["transport"]) == {
        "errors", "retransmits", "timeouts", "corrupt_replies"}


def test_report_prefers_registry_transport_totals():
    """Satellite 2, reader side: a kind="metrics" snapshot in the
    stream IS the transport-totals source; classified transport events
    remain the fallback — and on a clean stream both agree."""
    tev = [{"kind": "transport", "event": "timeouts", "replica": 0},
           {"kind": "transport", "event": "timeouts", "replica": 1},
           {"kind": "transport", "event": "corrupt_replies",
            "replica": 0}]
    met = {"kind": "metrics", "metrics": [
        {"name": "transport_timeouts", "type": "counter",
         "labels": {"link": "0"}, "value": 1},
        {"name": "transport_timeouts", "type": "counter",
         "labels": {"link": "1"}, "value": 1},
        {"name": "transport_corrupt_replies", "type": "counter",
         "labels": {"link": "0"}, "value": 1},
        {"name": "transport_rtt_ms", "type": "histogram",
         "labels": {"link": "0"}, "count": 3, "sum": 1.0,
         "buckets": [1.0], "counts": [3, 0]}]}
    with_reg = report_lib.summarize(tev + [met])
    fallback = report_lib.summarize(tev)
    tr_reg = with_reg["serving"]["transport"]
    tr_ev = fallback["serving"]["transport"]
    for k in ("timeouts", "corrupt_replies"):
        assert tr_reg[k] == tr_ev[k], k
    assert tr_reg["retransmits"] == 0          # zero-filled, not absent
    assert tr_reg["events"] == 3


# ---------------------------------------------------------------------------
# obs.top: sparklines + the metrics dashboard block
# ---------------------------------------------------------------------------

def test_sparkline_shapes():
    assert top_lib.sparkline([]) == ""
    assert top_lib.sparkline([5, 5, 5]) == "▁▁▁"
    ramp = top_lib.sparkline([0, 1, 2, 3, 4, 5, 6, 7])
    assert ramp[0] == "▁" and ramp[-1] == "█"
    assert len(top_lib.sparkline(list(range(100)), width=24)) == 24


def test_top_renders_registry_block_from_hub():
    hub = _ticking_hub()
    c = hub.counter("fleet_ticks", "ticks", replica="0")
    for _ in range(6):
        c.inc()
    hub.gauge("depth").set(3)
    h = hub.histogram("lat_ms", buckets=[1.0, 10.0])
    for v in (0.5, 2.0, 2.0, 20.0):
        h.observe(v)
    frame = top_lib.render(hub=hub)
    assert "-- metrics (registry) --" in frame
    assert "fleet_ticks{replica=0}" in frame
    assert "total=6.00" in frame
    assert "n=4" in frame                      # histogram line
    assert any(ch in frame for ch in "▁▂▃▄▅▆▇█")


def test_top_once_renders_metrics_from_jsonl(tmp_path, capsys):
    """The offline path the --once CLI exercises: kind="metrics"
    snapshots in the telemetry JSONL become sparkline history."""
    snaps = []
    for tick in (1, 2, 3):
        snaps.append({"kind": "metrics", "tick": tick, "metrics": [
            {"name": "fleet_ticks", "type": "counter", "labels": {},
             "value": float(tick * 2)}]})
    p = tmp_path / "tel.jsonl"
    p.write_text("\n".join(json.dumps(s) for s in snaps) + "\n")
    rc = top_lib.main(["--jsonl", str(p), "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "-- metrics (registry) --" in out
    assert "fleet_ticks" in out and "total=6.00" in out


# ---------------------------------------------------------------------------
# SLO monitor → registry gauges (satellite 3)
# ---------------------------------------------------------------------------

def _req(ms):
    return {"kind": "request", "finish_reason": "length",
            "ttft_ms": ms, "tpot_ms": ms / 10.0, "wall_ms": ms * 2.0,
            "new_tokens": 4}


def test_slo_monitor_publishes_gauges_report_identical():
    from paddle_tpu.obs import SLOMonitor
    hub = _ticking_hub()
    with_m = SLOMonitor(metrics=hub)
    without = SLOMonitor()
    for i in range(20):
        rec = _req(10.0 + i)
        with_m.observe(rec)
        without.observe(rec)
    # report() is byte-identical with the registry attached
    assert (json.dumps(with_m.report(), sort_keys=True)
            == json.dumps(without.report(), sort_keys=True))
    rows = {r["name"]: r["value"] for r in hub.snapshot()}
    rep = with_m.report()
    for m in ("ttft_ms", "tpot_ms", "wall_ms"):
        for p in (50, 95, 99):
            assert rows[f"slo_{m}_p{p}"] == pytest.approx(
                rep[f"{m}_p{p}"]), (m, p)
    assert rows["slo_burn_rate"] == pytest.approx(rep["burn_rate"])


# ---------------------------------------------------------------------------
# P² adversarial streams (satellite 4)
# ---------------------------------------------------------------------------

def test_p2_constant_stream_is_exact_at_any_length():
    for n in (1, 4, 5, 6, 100):
        for p in (50, 95, 99):
            est = P2Quantile(p)
            for _ in range(n):
                est.observe(7.25)
            assert est.value() == 7.25, (n, p)


def test_p2_two_value_alternation():
    vals = []
    ests = {p: P2Quantile(p) for p in (50, 95, 99)}
    for i in range(1000):
        v = float(i % 2)
        vals.append(v)
        for est in ests.values():
            est.observe(v)
    # tails pin to the upper value like the exact rule; the median may
    # sit anywhere inside the two-point support but never outside it
    assert ests[95].value() == pytest.approx(1.0)
    assert ests[99].value() == pytest.approx(1.0)
    assert 0.0 <= ests[50].value() <= 1.0


def test_p2_monotone_ramps_track_nearest_rank():
    for direction in (1, -1):
        stream = [float(i) for i in range(1, 1001)][::direction]
        for p in (50, 95, 99):
            est = P2Quantile(p)
            for v in stream:
                est.observe(v)
            exact = percentile(stream, p)
            assert est.value() == pytest.approx(exact, rel=0.01), (
                direction, p, est.value(), exact)


def test_p2_five_sample_boundary():
    """n < 5 answers the exact nearest-rank rule; crossing into marker
    mode the estimate may jump (markers initialize to the 5 sorted
    samples regardless of p) but stays inside the observed range."""
    stream = [5.0, 1.0, 4.0, 2.0, 3.0, 6.0, 0.5]
    for p in (50, 95, 99):
        est = P2Quantile(p)
        seen = []
        for v in stream:
            est.observe(v)
            seen.append(v)
            exact = percentile(seen, p)
            if len(seen) < 5:
                assert est.value() == exact, (p, len(seen))
            else:
                assert min(seen) <= est.value() <= max(seen)
                assert abs(est.value() - exact) <= max(seen) - min(seen)
    # p50 specifically stays exact THROUGH the boundary: the middle
    # marker initializes to the median
    est = P2Quantile(50)
    for v in stream[:5]:
        est.observe(v)
    assert est.value() == 3.0
