"""Serving-fleet resilience tests (ISSUE 11): the loadgen traffic shapes,
the router's placement/health/shed policy, the SLO admission orders, and
the acceptance contract — the kill-anywhere sweep: a replica killed
before admit / post-prefill / mid-decode / during drain, with every
request reaching a terminal ``finish_reason`` (retried lineage intact),
zero retraces and zero leaked KV blocks on every surviving replica.

Everything runs on a :class:`SimClock` advanced a fixed ``dt`` per fleet
tick, so arrivals, heartbeat staleness, deadlines and predictions are
deterministic functions of tick counts — the drills replay identically
on every run."""

import collections
import tempfile

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models import TransformerLM
from paddle_tpu.obs import (InMemorySink, Telemetry, percentile,
                            summarize_requests)
from paddle_tpu.serve import (ContinuousBatchingScheduler, DecodeEngine,
                              ServingFleet, SimClock)
from paddle_tpu.serve.loadgen import make_workload, workload_stats
from paddle_tpu.train import FaultSchedule

V, W, DIM, LAYERS, HEADS, FFN = 64, 24, 32, 2, 4, 64
BS = 4                                    # block size

# sim-time constants: dt per tick, heartbeat timeout (2.5 ticks)
DT, HB = 0.1, 0.25


@pytest.fixture(scope="module")
def model_and_vars():
    model = TransformerLM(vocab=V, dim=DIM, num_layers=LAYERS,
                          num_heads=HEADS, ffn_hidden=FFN, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))
    return model, vs


def _greedy_oracle(model, vs, prompt, n_new):
    fwd = jax.jit(lambda v, i: model.apply(v, i))
    seq, out = list(prompt), []
    for _ in range(n_new):
        pad = np.zeros((1, W), np.int32)
        pad[0, :len(seq)] = seq
        logits = fwd(vs, jnp.asarray(pad))
        tok = int(np.argmax(np.asarray(logits[0, len(seq) - 1])))
        out.append(tok)
        seq.append(tok)
    return out


def _fleet(model, vs, n, *, telemetry=None, faults=None, clock=None,
           max_slots=2, **kw):
    return ServingFleet.from_model(
        model, vs, n,
        engine_kwargs=dict(max_slots=max_slots, block_size=BS),
        telemetry=telemetry, faults=faults,
        clock=clock if clock is not None else SimClock(),
        heartbeat_timeout_s=HB, est_tick_s=DT,
        root=tempfile.mkdtemp(prefix="paddle_tpu_fleet_test_"), **kw)


def _assert_lineage(mem, frs):
    """One terminal record per rid, retried records <= retries, terminal
    reason matches the fleet's."""
    by_rid = collections.defaultdict(list)
    for r in mem.by_kind("request"):
        by_rid[r["rid"]].append(r)
    for fr in frs:
        recs = by_rid[fr.rid]
        terminal = [r for r in recs if r["finish_reason"] != "retried"]
        assert len(terminal) == 1, (fr.rid, recs)
        assert terminal[0]["finish_reason"] == fr.finish_reason
        retried = [r for r in recs if r["finish_reason"] == "retried"]
        assert len(retried) <= fr.retries


def _assert_survivor_invariants(fleet, exclude=()):
    """Zero retraces and zero leaked blocks on every replica that did
    not die (the acceptance drill's surviving-engine contract)."""
    for w in fleet.workers:
        if w.replica_id in exclude or w.killed or w.state == "dead":
            continue
        cache = w.engine.cache
        assert cache.free_blocks == cache.num_blocks - 1, \
            f"replica {w.replica_id} leaked blocks"
        counts = w.engine.compile_counts()
        assert set(counts.values()) <= {0, 1}, counts
        if w.engine.ticks > 0:
            assert counts == {"prefill": 1, "tick": 1}


# ---------------------------------------------------------------------------
# loadgen: seeded traffic shapes
# ---------------------------------------------------------------------------

def test_loadgen_deterministic_shapes_and_sessions():
    kw = dict(seed=7, rate_rps=20.0, arrival="bursty", prompt_len=(2, 10),
              max_new=(2, 8), n_sessions=3, session_prefix_len=4,
              p_session=0.7, deadline_s=(1.0, 5.0), p_deadline=0.5,
              priorities=(0, 1), priority_weights=(0.7, 0.3),
              max_total=W)
    a = make_workload(40, V, **kw)
    b = make_workload(40, V, **kw)
    assert [(g.at_s, g.prompt, g.max_new_tokens, g.deadline_s, g.priority,
             g.session_id) for g in a] == \
           [(g.at_s, g.prompt, g.max_new_tokens, g.deadline_s, g.priority,
             g.session_id) for g in b]                 # same seed, same trace
    c = make_workload(40, V, **{**kw, "seed": 8})
    assert [g.prompt for g in a] != [g.prompt for g in c]
    # arrivals monotone, lengths within bounds + capacity clamp
    ats = [g.at_s for g in a]
    assert ats == sorted(ats)
    for g in a:
        assert 1 <= len(g.prompt) <= 10
        assert len(g.prompt) + g.max_new_tokens <= W
        assert g.priority in (0, 1)
    # sessions share their prefix verbatim
    by_sid = collections.defaultdict(list)
    for g in a:
        if g.session_id is not None:
            by_sid[g.session_id].append(g.prompt)
    assert by_sid, "p_session=0.7 over 40 requests produced no sessions"
    for prompts in by_sid.values():
        if len(prompts) > 1:
            pfx = prompts[0][:4]
            assert all(p[:4] == pfx for p in prompts)
    stats = workload_stats(a)
    assert stats["n"] == 40 and stats["with_session"] > 0
    assert stats["with_deadline"] > 0
    with pytest.raises(ValueError, match="arrival"):
        make_workload(4, V, arrival="nope")
    # review fix: a 0 lower bound is a count floor of 1, not a log crash
    zero_lo = make_workload(6, V, seed=1, prompt_len=(0, 6),
                            max_new=(1, 4))
    assert all(len(g.prompt) >= 1 for g in zero_lo)


def test_workload_stats_shareable_prefix_ratio():
    """ISSUE 12 satellite: workload_stats reports the shareable-prefix
    ratio of a trace — the number the fleet gate sizes its expected
    prefix-cache hits from. Session traces share their prefix on every
    repeat visit; random traces share ~nothing."""
    kw = dict(seed=5, prompt_len=(9, 12), max_new=(2, 4), max_total=W)
    sess = make_workload(30, V, n_sessions=3, session_prefix_len=6,
                         p_session=1.0, **kw)
    st = workload_stats(sess)
    assert st["prompt_tokens_total"] > 0
    # 27 repeat visits x 6-token prefix, minimum (same-session repeats)
    assert st["shareable_prefix_tokens"] >= 20
    assert 0 < st["shareable_prefix_ratio"] <= 1
    rand = make_workload(30, V, n_sessions=0, **kw)
    st2 = workload_stats(rand)
    assert st2["shareable_prefix_ratio"] < st["shareable_prefix_ratio"]
    assert workload_stats([]) == {"n": 0}


def test_affinity_routes_sessions_onto_warm_prefix_caches(model_and_vars):
    """ISSUE 12: router session affinity now has a MEASURED payoff — a
    session trace played with affinity on lands repeat visits on the
    replica already holding the session's prefix blocks, so fleet-wide
    prefix-cache hits exceed the affinity-off (pure least-loaded)
    placement, with identical terminal outcomes."""
    model, vs = model_and_vars

    def run(affinity):
        fleet = _fleet(model, vs, 2, affinity=affinity, shed=False,
                       max_slots=4)
        wl = make_workload(14, V, seed=2, rate_rps=40.0,
                           n_sessions=2, session_prefix_len=2 * BS,
                           p_session=1.0, prompt_len=(9, 11),
                           max_new=(6, 9), sigma=0.3, max_total=W)
        frs = fleet.play(wl, dt_s=DT)
        assert all(fr.done for fr in frs)
        return fleet.stats()

    on, off = run(True), run(False)
    assert on["prefix_hit_blocks"] > off["prefix_hit_blocks"]
    # the payoff also rides each replica's heartbeat payload
    fleet = _fleet(model, vs, 1, shed=False, max_slots=4)
    from paddle_tpu.parallel import multihost
    fleet.workers[0].beat(fleet.clock())
    beats = multihost.read_heartbeats(fleet.root)
    assert "prefix_hit_blocks" in beats[0]


# ---------------------------------------------------------------------------
# engine: structured admission probe (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def test_admit_probe_structured_reasons(model_and_vars):
    model, vs = model_and_vars
    # pool of 3 usable blocks, 2 slots
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS, num_blocks=4)
    p = eng.admit_probe(2 * W)
    assert (not p.ok) and p.reason == "width"
    p = eng.admit_probe(8)
    assert p.ok and p.reason is None and p.blocks_needed == 2
    p = eng.admit_probe(16)                       # needs 4 > 3 free
    assert (not p.ok) and p.reason == "blocks" and p.free_blocks == 3
    eng.admit(0, [1, 2, 3])
    eng.admit(1, [4, 5])
    p = eng.admit_probe(4)
    assert (not p.ok) and p.reason == "slots" and p.free_slots == 0
    # can_admit keeps the historical contract: slots excluded
    assert eng.can_admit(4) is True
    assert eng.can_admit(16) is False


# ---------------------------------------------------------------------------
# scheduler: SLO admission orders + submit-time shedding
# ---------------------------------------------------------------------------

def test_scheduler_sjf_and_priority_orders(model_and_vars):
    model, vs = model_and_vars
    for order, expect_first in (("sjf", "short"), ("priority", "vip")):
        eng = DecodeEngine(model, vs, max_slots=1, block_size=BS)
        clock = SimClock()
        sched = ContinuousBatchingScheduler(eng, order=order, clock=clock)
        long_ = sched.submit([1, 2, 3], 8, priority=0)
        short = sched.submit([4, 5], 2, priority=0)
        vip = sched.submit([6, 7], 8, priority=3)
        while sched.step():
            clock.advance(DT)
        done = {"long": long_, "short": short, "vip": vip}
        first = min(done, key=lambda k: done[k].first_token_ts)
        assert first == expect_first, (order, first)
        assert all(r.finish_reason == "length" for r in done.values())
    # fcfs baseline admits in arrival order
    eng = DecodeEngine(model, vs, max_slots=1, block_size=BS)
    sched = ContinuousBatchingScheduler(eng, order="fcfs")
    a = sched.submit([1, 2, 3], 8)
    b = sched.submit([4, 5], 2)
    sched.run()
    assert a.first_token_ts < b.first_token_ts
    with pytest.raises(ValueError, match="order"):
        ContinuousBatchingScheduler(eng, order="lifo")


def test_scheduler_shed_rejects_fast(model_and_vars):
    """With a tick-time estimate, a deadline-carrying request whose
    predicted completion blows its deadline is rejected at SUBMIT:
    finish_reason="shed", no slot, no blocks, one telemetry record."""
    model, vs = model_and_vars
    mem = InMemorySink()
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS,
                       telemetry=Telemetry(sinks=[mem]))
    clock = SimClock()
    sched = ContinuousBatchingScheduler(eng, shed=True, est_tick_s=1.0,
                                        clock=clock)
    free0 = eng.cache.free_blocks
    # backlog: 2 slots x 10-token budgets fill the predicted queue
    keep = [sched.submit([1, 2, 3], 10) for _ in range(2)]
    # 10 pending + 10/2 queue ticks + 4 run ticks >> 3s deadline: shed
    shed = sched.submit([4, 5], 4, deadline_s=3.0)
    assert shed.done and shed.finish_reason == "shed"
    assert shed.slot is None and shed.tokens == []
    assert eng.cache.free_blocks == free0    # shed took no blocks
    # a loose deadline still queues
    ok = sched.submit([6, 7], 2, deadline_s=100.0)
    while sched.step():
        clock.advance(1.0)
    assert all(r.finish_reason == "length" for r in keep + [ok])
    recs = {r["rid"]: r for r in mem.by_kind("request")}
    assert recs[shed.rid]["finish_reason"] == "shed"
    # without evidence (no est_tick_s), nothing is shed
    eng2 = DecodeEngine(model, vs, max_slots=1, block_size=BS)
    s2 = ContinuousBatchingScheduler(eng2, shed=True)
    r = s2.submit([1, 2], 2, deadline_s=0.001)
    assert not r.done and len(s2.queue) == 1


def test_scheduler_idle_gap_does_not_poison_tick_estimate(model_and_vars):
    """Review fix: the tick-time EMA only folds deltas between
    consecutive BUSY steps — an idle lull between bursts is think time,
    and must not inflate est_tick_s into shedding against an empty
    engine."""
    model, vs = model_and_vars
    eng = DecodeEngine(model, vs, max_slots=2, block_size=BS)
    clock = SimClock()
    sched = ContinuousBatchingScheduler(eng, shed=True, est_tick_s=0.1,
                                        clock=clock)
    sched.submit([1, 2, 3], 3)
    while sched.step():
        clock.advance(0.1)
    assert sched.est_tick_s == pytest.approx(0.1)
    clock.advance(1000.0)                   # a long idle lull
    ok = sched.submit([4, 5], 2, deadline_s=5.0)
    assert not ok.done, "idle gap was folded into est_tick_s"
    while sched.step():
        clock.advance(0.1)
    assert ok.finish_reason == "length"
    assert sched.est_tick_s == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# router: affinity + least-loaded placement
# ---------------------------------------------------------------------------

def test_router_affinity_and_least_loaded(model_and_vars):
    model, vs = model_and_vars
    fleet = _fleet(model, vs, 2)
    # session 9 pins to its first replica across submissions
    a = fleet.submit([1, 2, 3], 3, session_id=9)
    spread = [fleet.submit([4, 5, 6], 3) for _ in range(2)]
    b = fleet.submit([7, 8], 3, session_id=9)
    assert a.replica == b.replica                    # affinity
    assert {r.replica for r in spread + [a]} == {0, 1}   # least-loaded
    while fleet.outstanding():
        fleet.tick()
        fleet.clock.advance(DT)
    assert all(fr.finish_reason == "length"
               for fr in fleet.requests.values())
    _assert_survivor_invariants(fleet)


def test_router_affinity_yields_before_shedding(model_and_vars, nprng):
    """Review fix: a session pinned to a drowning replica falls back to
    least-loaded before a terminal shed verdict — losing prefix
    locality beats losing the request."""
    model, vs = model_and_vars
    fleet = _fleet(model, vs, 2)
    pin = fleet.submit([1, 2, 3], 3, session_id=5)
    # bury the pinned replica in backlog (no deadlines: nothing sheds)
    for _ in range(6):
        fleet.submit(list(nprng.randint(1, V, 4)), 10,
                     session_id=5)
    busy = fleet.workers[pin.replica]
    assert busy.scheduler.pending_new_tokens() > 40
    # deadline the pinned replica cannot meet, the idle one trivially can
    saved = fleet.submit([7, 8], 2, deadline_s=1.5, session_id=5)
    assert saved.finish_reason != "shed"
    assert saved.replica != pin.replica
    while fleet.outstanding():
        fleet.tick()
        fleet.clock.advance(DT)
    assert saved.finish_reason == "length"
    # the session re-pinned to the fallback replica
    assert fleet.router.sessions[5] == saved.replica


def test_router_session_map_is_lru_bounded(model_and_vars):
    model, vs = model_and_vars
    fleet = _fleet(model, vs, 2)
    fleet.router.max_sessions = 3
    for sid in range(5):
        fleet.router.route(prompt_len=2, max_new_tokens=2,
                           session_id=sid)
    assert len(fleet.router.sessions) == 3
    assert set(fleet.router.sessions) == {2, 3, 4}    # oldest evicted
    fleet.router.route(prompt_len=2, max_new_tokens=2, session_id=2)
    fleet.router.route(prompt_len=2, max_new_tokens=2, session_id=5)
    # the refresh of 2 saved it; 3 (now coldest) was evicted for 5
    assert set(fleet.router.sessions) == {4, 2, 5}


def test_fleet_matches_single_engine_tokens(model_and_vars, nprng):
    """A healthy fleet is semantically invisible: each request's tokens
    equal the greedy full-forward oracle."""
    model, vs = model_and_vars
    fleet = _fleet(model, vs, 2)
    prompts = [list(nprng.randint(1, V, nprng.randint(2, 7)))
               for _ in range(4)]
    frs = [fleet.submit(p, 4) for p in prompts]
    while fleet.outstanding():
        fleet.tick()
        fleet.clock.advance(DT)
    for p, fr in zip(prompts, frs):
        assert fr.tokens == _greedy_oracle(model, vs, p, 4)


# ---------------------------------------------------------------------------
# acceptance: the kill-anywhere sweep
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("phase,kill_tick,drain_tick", [
    ("before-admit", 0, None),       # killed before its first step
    ("post-prefill", 1, None),       # admitted + first token, no decode
    ("mid-decode", 3, None),         # several decode ticks in
    ("during-drain", 3, 2),          # drained at 2, killed draining at 3
])
def test_fleet_kill_anywhere_sweep(model_and_vars, nprng, phase,
                                   kill_tick, drain_tick):
    """Kill replica 0 at every lifecycle phase: every request reaches a
    terminal finish_reason, requests stranded on the dead replica carry
    finish_reason="retried" lineage and complete with the oracle's
    tokens on a survivor, and surviving engines keep zero retraces and
    zero leaked blocks."""
    model, vs = model_and_vars
    mem = InMemorySink()
    faults = FaultSchedule(kill_replica_at_tick=(kill_tick, 0))
    n = 3 if drain_tick is not None else 2
    fleet = _fleet(model, vs, n, telemetry=Telemetry(sinks=[mem]),
                   faults=faults)
    prompts = [list(nprng.randint(1, V, 4)) for _ in range(6)]
    frs = [fleet.submit(p, 6) for p in prompts]
    assert {fr.replica for fr in frs} >= {0, 1}      # both got traffic
    drains = {drain_tick: 0} if drain_tick is not None else {}
    for t in range(400):
        if t in drains:
            fleet.drain(drains[t])
        if not fleet.outstanding():
            break
        fleet.tick()
        fleet.clock.advance(DT)
    assert not fleet.outstanding(), fleet.stats()
    assert all(fr.finish_reason == "length" for fr in frs)
    retried = [fr for fr in frs if fr.retries > 0]
    assert retried, f"{phase}: kill touched no request"
    assert all(0 in fr.attempts for fr in retried)
    # retried requests regenerate the oracle's exact tokens elsewhere
    for fr in retried[:2]:
        assert fr.tokens == _greedy_oracle(
            model, vs, fr.prompt, fr.max_new_tokens)
    _assert_lineage(mem, frs)
    _assert_survivor_invariants(fleet, exclude=(0,))
    assert fleet.stats()["finish_reasons"] == {"length": 6}


def test_fleet_drain_reroutes_queue_and_releases(model_and_vars, nprng):
    model, vs = model_and_vars
    mem = InMemorySink()
    fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]))
    # overload replica queues so the drained one holds queued requests
    frs = [fleet.submit(list(nprng.randint(1, V, 4)), 6)
           for _ in range(8)]
    fleet.tick(); fleet.clock.advance(DT)
    w0 = fleet.workers[0]
    assert w0.scheduler.running and w0.scheduler.queue
    queued_rids = {r.rid for r in w0.scheduler.queue}
    running_rids = {r.rid for r in w0.scheduler.running.values()}
    fleet.drain(0)
    assert w0.state == "draining"
    # queued requests left immediately (retried lineage), running stayed
    assert not w0.scheduler.queue
    assert {r.rid for r in w0.scheduler.running.values()} == running_rids
    for _ in range(300):
        if not fleet.outstanding():
            break
        fleet.tick()
        fleet.clock.advance(DT)
    assert w0.state == "released"
    assert all(fr.finish_reason == "length" for fr in frs)
    # running slots finished ON the draining replica (not resubmitted)
    assert all(fleet.requests[r].retries == 0 for r in running_rids)
    assert all(fleet.requests[r].retries >= 1 for r in queued_rids)
    events = [r["event"] for r in mem.by_kind("replica")]
    assert events.count("draining") == 1 and events.count("released") == 1
    _assert_lineage(mem, frs)
    _assert_survivor_invariants(fleet)       # incl. the released replica
    with pytest.raises(ValueError, match="last live"):
        fleet.drain(1)
    # ledger hygiene: everything terminal is prunable, nothing in flight
    assert not fleet._active
    assert fleet.prune_terminal() == len(frs) and not fleet.requests


def test_fleet_play_arrivals_relative_to_replay_start(model_and_vars,
                                                      nprng):
    """Review fix: play() measures arrivals from the START of the
    replay, not the clock's absolute value — a nonzero clock epoch
    (perf_counter, a mid-run SimClock) must not collapse the whole
    trace into one tick-0 burst."""
    model, vs = model_and_vars
    fleet = _fleet(model, vs, 2, clock=SimClock(t0=1234.5))
    wl = make_workload(6, V, seed=2, rate_rps=4.0, prompt_len=(2, 5),
                       max_new=(2, 4), max_total=W)
    assert wl[-1].at_s > 3 * DT          # spread over several ticks
    frs = fleet.play(wl, dt_s=DT)
    assert all(fr.finish_reason == "length" for fr in frs)
    # submit timestamps track the (offset) arrival spread, not one burst
    spread = max(fr.submit_ts for fr in frs) - min(fr.submit_ts
                                                   for fr in frs)
    assert spread >= 2 * DT, [fr.submit_ts for fr in frs]
    assert min(fr.submit_ts for fr in frs) >= 1234.5


def test_fleet_drain_cancelled_when_race_strands_capacity(model_and_vars,
                                                          nprng):
    """Review fix: drain() can race an unobserved kill (the victim still
    looks live). When parked work exists with zero live replicas, the
    fleet cancels the drain instead of stranding requests forever."""
    model, vs = model_and_vars
    mem = InMemorySink()
    faults = FaultSchedule(kill_replica_at_tick=(0, 0))
    fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]),
                   faults=faults)
    frs = [fleet.submit(list(nprng.randint(1, V, 4)), 6)
           for _ in range(4)]     # both replicas hold work
    fleet.tick()                  # kill fires; replica 0 LOOKS live
    fleet.clock.advance(DT)
    fleet.drain(1)                # guard passes — the race
    for _ in range(300):
        if not fleet.outstanding():
            break
        fleet.tick()
        fleet.clock.advance(DT)
    assert all(fr.finish_reason == "length" for fr in frs)
    assert fleet.workers[1].state == "live"       # drain was cancelled
    events = [r["event"] for r in mem.by_kind("replica")]
    assert "drain-cancelled" in events
    _assert_lineage(mem, frs)


def test_fleet_shed_under_overload(model_and_vars, nprng):
    """Tight deadlines against a saturated fleet: the router rejects
    fast (finish_reason="shed" with the structured reason), admitted
    requests still finish, and nothing leaks."""
    model, vs = model_and_vars
    mem = InMemorySink()
    fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]),
                   max_slots=2)
    frs = [fleet.submit(list(nprng.randint(1, V, 4)), 10,
                        deadline_s=2.0) for _ in range(10)]
    while fleet.outstanding():
        fleet.tick()
        fleet.clock.advance(DT)
    reasons = collections.Counter(fr.finish_reason for fr in frs)
    assert reasons["shed"] >= 1, reasons
    assert reasons["shed"] + reasons.get("length", 0) \
        + reasons.get("timeout", 0) == 10
    shed = [fr for fr in frs if fr.finish_reason == "shed"]
    assert all(fr.tokens == [] and fr.record["wall_ms"] == 0.0
               for fr in shed)
    recs = {r["rid"]: r for r in mem.by_kind("request")}
    assert all(recs[fr.rid].get("shed_reason") in ("delay", "blocks",
                                                   "slots")
               for fr in shed)
    _assert_lineage(mem, frs)
    _assert_survivor_invariants(fleet)


# ---------------------------------------------------------------------------
# idempotency faults: duplicate + dropped deliveries, the fenced zombie
# ---------------------------------------------------------------------------

def test_fleet_duplicate_submit_is_idempotent(model_and_vars, nprng):
    model, vs = model_and_vars
    mem = InMemorySink()
    faults = FaultSchedule(duplicate_submit_at=1)
    fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]),
                   faults=faults)
    frs = [fleet.submit(list(nprng.randint(1, V, 4)), 4)
           for _ in range(3)]
    while fleet.outstanding():
        fleet.tick()
        fleet.clock.advance(DT)
    assert fleet.duplicates_dropped == 1
    assert ("duplicate_submit_at", 1) in faults.fired
    assert all(fr.finish_reason == "length" for fr in frs)
    _assert_lineage(mem, frs)                  # exactly ONE terminal rec


def test_fleet_drop_submit_reconciles(model_and_vars, nprng):
    """A delivery lost after assignment (the lost-RPC fault): the
    reconcile sweep notices the replica never learned the rid and
    resubmits — the request completes with retries >= 1."""
    model, vs = model_and_vars
    mem = InMemorySink()
    faults = FaultSchedule(drop_submit_at=0)
    fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]),
                   faults=faults)
    frs = [fleet.submit(list(nprng.randint(1, V, 4)), 4)
           for _ in range(3)]
    assert frs[0].local is None                # delivery was eaten
    while fleet.outstanding():
        fleet.tick()
        fleet.clock.advance(DT)
    assert frs[0].finish_reason == "length" and frs[0].retries >= 1
    assert all(fr.finish_reason == "length" for fr in frs)
    assert frs[0].tokens == _greedy_oracle(model, vs, frs[0].prompt, 4)
    _assert_lineage(mem, frs)


def test_fleet_stalled_replica_fences_on_wake(model_and_vars, nprng):
    """A replica that stalls past the heartbeat timeout is declared dead
    and its requests re-homed; when it wakes it self-fences — every slot
    evicted, blocks freed, and it never completes a re-homed request
    (zero stale completions)."""
    model, vs = model_and_vars
    mem = InMemorySink()
    faults = FaultSchedule(stall_replica_at_tick=(1, 0, 12))
    fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]),
                   faults=faults)
    frs = [fleet.submit(list(nprng.randint(1, V, 4)), 6)
           for _ in range(4)]
    for _ in range(40):                       # run past the wake tick
        fleet.tick()
        fleet.clock.advance(DT)
        if not fleet.outstanding() and fleet.ticks > 15:
            break
    w0 = fleet.workers[0]
    assert w0.state == "dead" and w0._fenced
    cache = w0.engine.cache
    assert cache.free_blocks == cache.num_blocks - 1   # fence freed all
    assert not w0.scheduler.running and not w0.known
    assert all(fr.finish_reason == "length" for fr in frs)
    assert any(fr.retries > 0 and 0 in fr.attempts for fr in frs)
    assert fleet.stale_completions == 0
    _assert_lineage(mem, frs)
    _assert_survivor_invariants(fleet, exclude=(0,))


# ---------------------------------------------------------------------------
# autoscaler: hysteresis, replacement budget, heartbeat retirement
# (ISSUE 13)
# ---------------------------------------------------------------------------

def test_autoscaler_hysteresis_bounds_scale_events(model_and_vars,
                                                   nprng):
    """The acceptance drill for flapping: three bursts with idle gaps —
    a naive threshold policy would scale up at every burst head and
    down in every gap (>= 6 events). With cooldown + idle grace the
    event count is bounded, consecutive up/down decisions are spaced >=
    cooldown ticks apart, every scale-down routes through drain()
    (released, never dead), and zero requests are lost."""
    from paddle_tpu.serve import Autoscaler
    model, vs = model_and_vars
    mem = InMemorySink()
    scaler = Autoscaler(min_replicas=1, max_replicas=3, up_delay_s=1.5,
                        idle_grace_ticks=6, cooldown_ticks=8)
    fleet = _fleet(model, vs, 1, telemetry=Telemetry(sinks=[mem]),
                   autoscaler=scaler)
    frs = []
    for _burst in range(3):
        for _ in range(10):
            frs.append(fleet.submit(list(nprng.randint(1, V, 4)), 8))
        for _ in range(30):                # burst + idle gap
            fleet.tick()
            fleet.clock.advance(DT)
    for _ in range(100):
        if not fleet.outstanding():
            break
        fleet.tick()
        fleet.clock.advance(DT)
    assert all(fr.finish_reason == "length" for fr in frs)
    _assert_lineage(mem, frs)
    events = mem.by_kind("scale")
    # one stream, same ledger (emit stamps ts on the sink copy)
    assert [{k: v for k, v in e.items() if k != "ts"}
            for e in events] == scaler.events
    assert 2 <= len(events) <= 6, [  # naive threshold would flap >= 6
        (e["tick"], e["action"]) for e in events]
    assert {e["action"] for e in events} == {"up", "down"}
    updown = [e for e in events if e["action"] in ("up", "down")]
    gaps = [b["tick"] - a["tick"] for a, b in zip(updown, updown[1:])]
    assert all(g >= scaler.cooldown_ticks for g in gaps), gaps
    for e in events:                        # the telemetry schema
        assert e["reason"] in ("predicted-delay-breach",
                               "sustained-idle")
        assert e["replicas_after"] == e["replicas_before"] + (
            1 if e["action"] == "up" else -1)
    # scale-down went through drain(): released, with zero leaks
    released = [w for w in fleet.workers if w.state == "released"]
    assert released, [w.state for w in fleet.workers]
    _assert_survivor_invariants(fleet)
    # capacity returned to min on sustained idle
    assert sum(1 for w in fleet.workers if w.state == "live") == 1


def test_autoscaler_replaces_dead_replica_then_gives_up_loud(
        model_and_vars, nprng):
    from paddle_tpu.serve import Autoscaler, AutoscalerGaveUp
    model, vs = model_and_vars
    mem = InMemorySink()
    faults = FaultSchedule(kill_replica_at_tick=(2, 0))
    scaler = Autoscaler(min_replicas=2, max_replicas=3,
                        idle_grace_ticks=1000, cooldown_ticks=5,
                        max_replacements=1)
    fleet = _fleet(model, vs, 2, telemetry=Telemetry(sinks=[mem]),
                   faults=faults, autoscaler=scaler)
    frs = [fleet.submit(list(nprng.randint(1, V, 4)), 6)
           for _ in range(6)]
    for _ in range(300):
        if not fleet.outstanding():
            break
        fleet.tick()
        fleet.clock.advance(DT)
    assert all(fr.finish_reason == "length" for fr in frs)
    # the dead replica was cold-replaced: a third worker exists, live
    assert len(fleet.workers) == 3
    assert fleet.workers[2].state == "live"
    replaces = [e for e in scaler.events if e["action"] == "replace"]
    assert len(replaces) == 1 and scaler.replacements == 1
    assert replaces[0]["reason"] == "replica-dead"
    assert [r["kind"] for r in mem.by_kind("scale")] == ["scale"] * len(
        scaler.events)
    _assert_lineage(mem, frs)
    # budget exhausted -> give-up-loud with the ledger attached
    fleet.workers[2].kill()
    with pytest.raises(AutoscalerGaveUp) as e:
        for _ in range(40):
            fleet.tick()
            fleet.clock.advance(DT)
    assert e.value.events and e.value.events[0]["action"] == "replace"


def test_heartbeat_retired_on_release_and_death(model_and_vars, nprng):
    """ISSUE 13 satellite: released/dead replicas must not leave a live
    heartbeat file behind — the file is RETIRED (renamed, never
    deleted) so detect_dead_hosts stops re-reporting ghosts forever."""
    import os
    from paddle_tpu.parallel import multihost
    model, vs = model_and_vars
    faults = FaultSchedule(kill_replica_at_tick=(2, 1))
    fleet = _fleet(model, vs, 3, faults=faults)
    frs = [fleet.submit(list(nprng.randint(1, V, 4)), 4)
           for _ in range(4)]
    fleet.tick(); fleet.clock.advance(DT)
    fleet.drain(0)
    for _ in range(300):
        if not fleet.outstanding():
            break
        fleet.tick()
        fleet.clock.advance(DT)
    assert fleet.workers[0].state == "released"
    assert fleet.workers[1].state == "dead"
    assert all(fr.finish_reason == "length" for fr in frs)
    for rid in (0, 1):
        path = multihost.heartbeat_path(fleet.root, rid)
        assert not os.path.exists(path), f"ghost beat for replica {rid}"
        assert os.path.exists(path + ".retired")      # never deleted
    # the watchdog view: a full-root probe no longer reports the ghosts
    stale = multihost.detect_dead_hosts(fleet.root, HB,
                                        now=fleet.clock() + 100.0)
    assert 0 not in stale and 1 not in stale
    # retiring twice numbers the siblings instead of overwriting
    multihost.write_heartbeat(fleet.root, host_id=0, now=fleet.clock())
    assert multihost.retire_heartbeat(fleet.root, 0).endswith(
        ".retired.1")


def test_fault_schedule_describe_includes_process_points():
    faults = FaultSchedule(sigkill_replica_at_tick=(6, 0),
                           transport_hang_at=(3, 1),
                           corrupt_reply_at=(4, 2))
    d = faults.describe()
    assert d["sigkill_replica_at_tick"] == (6, 0)
    assert d["transport_hang_at"] == (3, 1)
    assert d["corrupt_reply_at"] == (4, 2)
    # one-shot: each point fires exactly once
    assert faults.sigkill_replica_for_tick(6) == 0
    assert faults.sigkill_replica_for_tick(6) is None
    assert faults.should_hang_transport(3, 1) is True
    assert faults.should_hang_transport(3, 1) is False
    assert faults.should_corrupt_reply(4, 2) is True
    assert faults.should_corrupt_reply(4, 2) is False
    assert [p for p, _ in faults.fired] == [
        "sigkill_replica_at_tick", "transport_hang_at",
        "corrupt_reply_at"]


# ---------------------------------------------------------------------------
# percentiles + goodput aggregation (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def test_percentile_nearest_rank():
    assert percentile([], 50) is None
    assert percentile([None, None], 99) is None
    assert percentile([3.0, 1.0, 2.0, None], 50) == 2.0
    assert percentile([1, 2, 3, 4], 50) == 2
    assert percentile([1, 2, 3, 4], 95) == 4
    assert percentile(range(1, 101), 99) == 99
    assert percentile([7.0], 99) == 7.0


def test_summarize_requests_goodput_and_lineage_filter():
    def rec(rid, reason, ttft=10.0, tpot=5.0, wall=100.0, deadline=None,
            new_tokens=4):
        return {"kind": "request", "rid": rid, "finish_reason": reason,
                "ttft_ms": ttft, "tpot_ms": tpot, "wall_ms": wall,
                "deadline_s": deadline, "new_tokens": new_tokens}

    records = [
        rec(0, "length", wall=100.0, deadline=1.0),        # met
        rec(1, "length", wall=5000.0, deadline=1.0),       # late
        rec(2, "timeout", wall=2000.0, deadline=1.0),      # missed
        rec(3, "shed", ttft=None, tpot=None, wall=0.0,
            deadline=1.0, new_tokens=0),                   # rejected
        rec(4, "retried", wall=50.0),                      # lineage only
        rec(4, "eos", wall=400.0),                         # its terminal
        {"kind": "decode_tick", "tick": 1},                # ignored
    ]
    s = summarize_requests(records)
    assert s["requests"] == 5 and s["retried_attempts"] == 1
    assert s["finish_reasons"] == {"length": 2, "timeout": 1,
                                   "shed": 1, "eos": 1}
    assert s["deadline_requests"] == 4 and s["deadline_met"] == 1
    assert s["goodput_pct"] == 25.0 and s["goodput_tokens"] == 4
    assert s["shed"] == 1 and s["timeout"] == 1
    assert s["ttft_ms_p50"] == 10.0
    assert s["wall_ms_p99"] == 5000.0      # retried row's wall excluded
    # review fix: the shed row's wall_ms=0 must not drag the latency
    # percentiles down (latency inputs: 100, 5000, 2000, 400)
    assert s["wall_ms_p50"] == 400.0
    assert summarize_requests([{"kind": "step"}]) is None


def test_report_summarize_includes_serving_block(tmp_path):
    """The obs.report CLI path grows the serving block when the JSONL
    carries request records."""
    import json
    from paddle_tpu.obs import report as report_lib
    path = tmp_path / "run.jsonl"
    rows = [
        {"kind": "request", "rid": 0, "finish_reason": "length",
         "ttft_ms": 12.0, "tpot_ms": 3.0, "wall_ms": 40.0,
         "deadline_s": 1.0, "new_tokens": 8},
        {"kind": "request", "rid": 1, "finish_reason": "shed",
         "ttft_ms": None, "tpot_ms": None, "wall_ms": 0.0,
         "deadline_s": 0.5, "new_tokens": 0},
        {"kind": "evict", "rid": 2, "where": "queued"},
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    s = report_lib.summarize(report_lib.load_records(str(path)))
    assert s["serving"]["requests"] == 2
    assert s["serving"]["shed"] == 1
    assert s["serving"]["goodput_pct"] == 50.0
    text = report_lib.format_summary(s)
    assert "serving requests" in text and "goodput under deadline" in text
    assert report_lib.main([str(path)]) == 0


def test_summarize_scale_and_report_block(tmp_path):
    """ISSUE 13 satellite: kind="scale" events aggregate next to the
    request percentiles — up/down/replace counts, reasons, final
    capacity — and render in the report CLI."""
    import json
    from paddle_tpu.obs import summarize_scale
    from paddle_tpu.obs import report as report_lib

    def ev(action, reason, before, after, tick):
        return {"kind": "scale", "action": action, "reason": reason,
                "replicas_before": before, "replicas_after": after,
                "tick": tick}

    records = [
        ev("up", "predicted-delay-breach", 1, 2, 3),
        ev("replace", "replica-dead", 1, 2, 9),
        ev("down", "sustained-idle", 2, 1, 30),
        {"kind": "request", "rid": 0, "finish_reason": "length",
         "ttft_ms": 5.0, "tpot_ms": 2.0, "wall_ms": 20.0,
         "new_tokens": 3},
    ]
    s = summarize_scale(records)
    assert s == {"events": 3, "up": 1, "down": 1, "replace": 1,
                 "reasons": {"predicted-delay-breach": 1,
                             "replica-dead": 1, "sustained-idle": 1},
                 "final_replicas": 1, "max_replicas_seen": 2}
    assert summarize_scale([{"kind": "request"}]) is None
    path = tmp_path / "scale.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    out = report_lib.summarize(report_lib.load_records(str(path)))
    assert out["serving"]["scale"]["events"] == 3
    text = report_lib.format_summary(out)
    assert "autoscaler" in text and "scale events (up/down/repl)" in text
    # scale events WITHOUT request records still summarize + render
    path2 = tmp_path / "scale_only.jsonl"
    path2.write_text("\n".join(json.dumps(r) for r in records[:3]) + "\n")
    out2 = report_lib.summarize(report_lib.load_records(str(path2)))
    assert out2["serving"]["scale"]["replace"] == 1
    assert "autoscaler" in report_lib.format_summary(out2)
