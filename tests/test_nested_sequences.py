"""Nested (sub-)sequence machinery — the analog of the reference's
``test_RecurrentGradientMachine.cpp`` nested-vs-plain equivalence suite
(``sequence_nest_rnn.conf`` vs ``sequence_rnn.conf``)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import (NestedSeqBatch, pack_nested_sequences,
                                      unpack_nested_sequences)
from paddle_tpu.nn.recurrent import RNN, HierarchicalRNN, SimpleRNNCell
from paddle_tpu.nn.sequence_ops import (select_sub_sequences,
                                        starts_from_segments, sub_seq_last,
                                        sub_seq_pool)


def _nested_data(seed=0, B=3, D=4):
    rng = np.random.RandomState(seed)
    seqs = []
    for _ in range(B):
        n_sub = rng.randint(1, 4)
        seqs.append([rng.normal(size=(rng.randint(1, 5), D)).astype(np.float32)
                     for _ in range(n_sub)])
    return seqs


# ----------------------------------------------------------- representation

def test_nested_batch_roundtrip_and_masks():
    seqs = _nested_data()
    nb = NestedSeqBatch.from_lists(seqs)
    assert nb.data.ndim == 4
    tm = np.asarray(nb.token_mask())
    sm = np.asarray(nb.subseq_mask())
    for i, subs in enumerate(seqs):
        assert sm[i].sum() == len(subs)
        for j, ss in enumerate(subs):
            assert tm[i, j].sum() == len(ss)
            np.testing.assert_allclose(
                np.asarray(nb.data)[i, j, :len(ss)], ss)


def test_pack_nested_roundtrip():
    seqs = _nested_data(seed=1, B=5)
    data, seg, sub, pos = pack_nested_sequences(seqs, row_len=16)
    got = unpack_nested_sequences(data, seg, sub)
    want = [[np.asarray(ss) for ss in subs] for subs in seqs]
    # order is not preserved; match by content
    def key(subs):
        return tuple(np.round(np.concatenate(subs).ravel(), 5).tolist())
    assert sorted(map(key, got)) == sorted(map(key, want))
    # positions restart at each subsequence
    for r in range(data.shape[0]):
        for t in range(data.shape[1]):
            if sub[r, t] > 0 and (t == 0 or sub[r, t] != sub[r, t - 1]):
                assert pos[r, t] == 0


def test_sub_segment_ids_nest_inside_segments():
    seqs = _nested_data(seed=2, B=4)
    data, seg, sub, _ = pack_nested_sequences(seqs, row_len=16)
    # every token in a subsequence belongs to exactly one outer segment
    for r in range(seg.shape[0]):
        for u in np.unique(sub[r]):
            if u == 0:
                continue
            outer = seg[r][sub[r] == u]
            assert len(np.unique(outer)) == 1 and outer[0] > 0


# ------------------------------------------------------------- sub-seq ops

def test_sub_seq_pool_and_last_oracle():
    seqs = _nested_data(seed=3)
    nb = NestedSeqBatch.from_lists(seqs)
    avg = np.asarray(sub_seq_pool(nb.data, nb.sub_lengths, "average"))
    last = np.asarray(sub_seq_last(nb.data, nb.sub_lengths))
    for i, subs in enumerate(seqs):
        for j, ss in enumerate(subs):
            np.testing.assert_allclose(avg[i, j], ss.mean(0), rtol=1e-5,
                                       atol=1e-6)
            np.testing.assert_allclose(last[i, j], ss[-1], rtol=1e-5)


def test_select_sub_sequences():
    seqs = _nested_data(seed=4)
    nb = NestedSeqBatch.from_lists(seqs)
    idx = jnp.asarray([[0, -1], [0, 0], [0, -1]], jnp.int32)
    gx, gl = select_sub_sequences(nb.data, nb.sub_lengths, idx)
    assert gx.shape[1] == 2
    np.testing.assert_allclose(np.asarray(gx[0, 0]),
                               np.asarray(nb.data[0, 0]))
    assert np.asarray(gl)[0, 1] == 0 and np.asarray(gx[0, 1]).sum() == 0


# ------------------------------------------- nested-vs-plain RNN equivalence

def test_hierarchical_inner_equals_flat_rnn():
    """The inner recurrence over each subsequence must equal a plain RNN run
    on the subsequences as independent sequences (the reference's
    sequence_nest_rnn.conf == sequence_rnn.conf assertion)."""
    seqs = _nested_data(seed=5)
    nb = NestedSeqBatch.from_lists(seqs)
    hrnn = HierarchicalRNN(SimpleRNNCell(8), SimpleRNNCell(6))
    params = hrnn.init(jax.random.PRNGKey(0), nb.data, nb.sub_lengths,
                      nb.num_subseqs)
    inner_out, outer_out = hrnn.apply(params, nb.data, nb.sub_lengths,
                                      nb.num_subseqs)

    # plain RNN with the same inner weights on the flattened view
    flat = nb.flat()
    inner_params = params["params"]["HierarchicalRNN_0"]["inner"]
    from paddle_tpu.core.sequence import length_mask
    flat_out, _ = hrnn.inner.apply(
        {"params": {"inner": inner_params}}, flat.data,
        mask=length_mask(flat.lengths, flat.max_len))
    B, S, T = nb.data.shape[:3]
    flat_out = np.asarray(flat_out).reshape(B, S, T, -1)
    tm = np.asarray(nb.token_mask())
    np.testing.assert_allclose(np.asarray(inner_out) * tm[..., None],
                               flat_out * tm[..., None], rtol=1e-5, atol=1e-6)
    assert outer_out.shape == (B, S, 6)


def test_packed_subsegment_rnn_equals_per_subsequence():
    """RNN over packed rows with sub-segment resets == RNN per subsequence
    (inner-recurrence boundary honored across packing)."""
    seqs = _nested_data(seed=6, B=4)
    data, seg, sub, _ = pack_nested_sequences(seqs, row_len=12)
    cell = SimpleRNNCell(5)
    rnn = RNN(cell)
    x = jnp.asarray(data)
    params = rnn.init(jax.random.PRNGKey(1), x)
    starts = starts_from_segments(jnp.asarray(sub))
    packed_out, _ = rnn.apply(params, x, segment_starts=starts)
    packed_out = np.asarray(packed_out)

    # oracle: run each subsequence separately through the same weights
    for subs in unpack_nested_sequences(data, seg, sub):
        pass  # content-matched below via position scan
    rows = data.shape[0]
    for r in range(rows):
        for u in np.unique(sub[r]):
            if u == 0:
                continue
            sel = np.flatnonzero(sub[r] == u)
            piece = jnp.asarray(data[r][sel])[None]
            want, _ = rnn.apply(params, piece)
            np.testing.assert_allclose(packed_out[r][sel], np.asarray(want)[0],
                                       rtol=1e-5, atol=1e-6)
